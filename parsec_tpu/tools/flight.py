"""Per-rank flight recorder: a bounded black box for post-mortems (ISSUE 20).

A killed or wedged rank must leave evidence instead of silence (ROADMAP
item 4's debugging substrate). On trigger — peer death (ptcomm
``broken_peers``), pool error, a watchdog stall, or a p99 breach vs the
EWMA baseline (all fired by :mod:`parsec_tpu.core.watchdog`), or any
caller of :func:`record` — the recorder dumps an attributed snapshot of

* the native trace rings' recent events (drained through the context's
  trace bridge and re-emitted as a standalone ``.pbp`` companion file,
  readable by ``tools/trace_reader`` like any trace),
* the unified counter registry and the latency-histogram summaries,
* the comm lane's last frame counters (``out_pending``, ``bytes_*``,
  ``frame_errors``, ``broken_peers``),

into ``--mca flight_dir`` as ``flight-r<rank>-<n>-<trigger>.json`` (+
``.pbp`` when events exist). BOUNDED black box: at most ``--mca
flight_max_dumps`` dumps per process, at most ``--mca
flight_max_events`` events per stream in the companion trace, and a
repeated trigger key (the same stall persisting across watchdog ticks)
is suppressed after its first dump — "a forced stall produces exactly
one flight record" is the ci-gate contract.

Everything is best-effort and off the hot path: a failed snapshot
section degrades to its error string in the dump, never an exception
out of the trigger site.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

from ..utils import mca, output
from ..utils.counters import LaneStats

mca.register("flight_dir", "",
             "Arm the flight recorder: attributed post-mortem dumps "
             "(counters JSON + recent-events .pbp) land here on trigger "
             "(watchdog stall, peer death, pool error, p99 breach). "
             "Empty = disabled", type=str)
mca.register("flight_max_events", 2048,
             "Per-stream event cap in a flight dump's companion .pbp "
             "(the bounded black box)", type=int)
mca.register("flight_max_dumps", 4,
             "Max flight dumps per process — a flapping trigger must "
             "not fill the disk", type=int)

#: exported as ``flight.*`` by install_native_counters
FLIGHT_STATS = LaneStats(
    triggers=0,      # record() calls (armed or not)
    dumps=0,         # dumps actually written
    suppressed=0,    # repeated-key / over-cap / unarmed triggers
    errors=0,        # dump attempts that failed
)

_mu = threading.Lock()
_seen: set = set()        # trigger keys already dumped (dedup)
_dump_no = 0


def _json_safe(v):
    from .metrics_server import _json_safe as js
    return js(v)


def _section(fn):
    """Run one snapshot section; a failure becomes its error string."""
    try:
        return fn()
    except Exception as e:  # noqa: BLE001 — the dump must still land
        return {"error": repr(e)}


def _comm_brief(ctx) -> Dict[str, Any]:
    rde = getattr(ctx, "comm", None)
    native = getattr(rde, "native", None)
    if native is None:
        return {}
    s = native.comm.stats()
    return {k: s.get(k, 0) for k in
            ("out_pending", "bytes_tx", "bytes_rx", "acts_tx", "acts_rx",
             "frame_errors", "broken_peers", "early_parked",
             "dropped_sends")}


def _snapshot_trace(ctx, path: str, max_events: int) -> int:
    """Re-emit the tail of the attached tracer's streams as a
    standalone .pbp (same dictionary, last ``max_events`` events per
    stream) after a blocking ring drain — the recent-events black box.
    Returns the event count written (0 = no companion file)."""
    prof = getattr(ctx, "profiling", None) if ctx is not None else None
    if prof is None:
        return 0
    ntrace = getattr(ctx, "_ntrace", None)
    if ntrace is not None:
        try:
            ntrace.drain_all(wait=True)   # land straggler ring events
        except Exception:  # noqa: BLE001 — dump what already landed
            pass
    from ..utils.trace import Profiling
    snap = Profiling()
    with prof._lock:
        snap.t0 = prof.t0
        entries = sorted(prof._dict.values(), key=lambda e: e.key)
        streams = [(s.name, list(s.events[-max_events:]))
                   for s in prof._streams]
    # keys are assigned sequentially, so re-adding in key order
    # reproduces the same key space the copied events reference
    for e in entries:
        snap.add_dictionary_keyword(e.name, e.attr, e.info_desc)
    n = 0
    for name, events in streams:
        if not events:
            continue
        st = snap.stream(name)
        st.events.extend(events)
        n += len(events)
    if n == 0:
        return 0
    snap.dump(path, backend="pbp")
    return n


def record(trigger: str, detail: Optional[Dict[str, Any]] = None, *,
           key: Optional[str] = None, ctx=None,
           dir: Optional[str] = None) -> Optional[str]:
    """Dump one attributed flight record; returns the JSON path or None
    (unarmed / suppressed / failed — counted either way).

    ``key`` dedups: the same key never dumps twice in one process (the
    watchdog passes ``watchdog_stall:<lane>`` so a persisting stall
    produces exactly one record). ``ctx`` (optional) supplies the trace
    bridge, tracer and comm lane for the events/comm sections.
    """
    global _dump_no
    FLIGHT_STATS["triggers"] += 1
    out_dir = dir if dir is not None else mca.get("flight_dir", "")
    if not out_dir:
        FLIGHT_STATS["suppressed"] += 1
        return None
    with _mu:
        k = key or trigger
        if k in _seen or _dump_no >= max(1, mca.get("flight_max_dumps", 4)):
            FLIGHT_STATS["suppressed"] += 1
            return None
        _seen.add(k)
        _dump_no += 1
        n = _dump_no
    rank = getattr(ctx, "my_rank", 0) if ctx is not None else 0
    if not rank:       # a rank-0-shaped local ctx: trust the trigger's
        rank = (detail or {}).get("rank", 0) or 0   # own attribution
    base = os.path.join(out_dir, f"flight-r{rank}-{n}-{trigger}")
    try:
        os.makedirs(out_dir, exist_ok=True)
        from ..utils.counters import counters, install_native_counters
        from ..utils.hist import histograms
        _section(install_native_counters)
        from ..core.watchdog import WATCHDOG_STATS
        pbp_path = base + ".pbp"
        nevents = _section(lambda: _snapshot_trace(
            ctx, pbp_path, max(1, mca.get("flight_max_events", 2048))))
        body = {
            "trigger": trigger,
            "key": key or trigger,
            "detail": detail or {},
            "ts": time.time(),
            "rank": rank,
            "pid": os.getpid(),
            "counters": _section(counters.snapshot),
            "percentiles": _section(lambda: histograms.summaries(ttl=0.0)),
            "comm": _section(lambda: _comm_brief(ctx)),
            "watchdog": _section(WATCHDOG_STATS.snapshot),
            "events": nevents if isinstance(nevents, int) else 0,
            "trace": os.path.basename(pbp_path)
            if isinstance(nevents, int) and nevents else None,
        }
        path = base + ".json"
        with open(path, "w") as f:
            json.dump(_json_safe(body), f, indent=1)
        FLIGHT_STATS["dumps"] += 1
        output.warning(f"flight record dumped: {path} "
                       f"(trigger={trigger}, {body['events']} events)")
        return path
    except Exception as e:  # noqa: BLE001 — the black box must not throw
        FLIGHT_STATS["errors"] += 1
        output.debug_verbose(1, "flight", f"dump failed: {e}")
        return None


def reset() -> None:
    """Drop the dedup set + dump counter (test isolation only)."""
    global _dump_no
    with _mu:
        _seen.clear()
        _dump_no = 0
