"""Lane stall watchdog: the machine explains its own stalls (ISSUE 20).

The PR 9 ABBA deadlock and the PR 10 pin race were both diagnosed by
hand with faulthandler dumps. This module makes that class of failure
self-detecting: a low-frequency monitor thread (armed by ``--mca
watchdog_stall_ms``, one tick every stall_ms/4) reads *existing*
per-lane progress counters — no new hot-path instrumentation, the PR 13
contract — and latches a stall when a lane holds work but its progress
counter stops moving for the threshold:

* **pool**: a scheduler-plane pool with ``queued + inflight > 0`` whose
  ``served`` count hasn't moved (``pool_stats`` per handle, attributed
  by pool name);
* **device**: a device lane with ``ptdev.inflight > 0`` and no retires
  (the registry's C-side samplers);
* **comm**: a comm lane whose sendq (``out_pending``) holds frames but
  neither ``bytes_tx`` nor ``acts_tx`` advances — a non-draining queue,
  not a busy one.

Each stall episode counts ONCE (``watchdog.{pool,comm,device}_stalls``),
degrades ``/health`` (``ok: false`` + the attributed stall list, via
:func:`health_report` — the metrics endpoint consults it per probe),
and fires exactly one attributed flight-record dump
(:mod:`parsec_tpu.tools.flight`); progress resuming clears the episode
(``watchdog.clears``) and restores ``/health``. The same tick also
watches for the flight recorder's other triggers: new ``broken_peers``
(peer death), a poisoned context (pool error), and a p99 breach vs an
EWMA baseline on the native latency histograms.

An idle-but-healthy pool (queued == 0) can never trip a rule — every
rule requires held work — which is the zero-false-positive contract
tests/test_pttel.py asserts.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Dict, List, Optional

from ..utils import mca, output
from ..utils.counters import LaneStats

mca.register("watchdog_stall_ms", 0,
             "Arm the lane stall watchdog: a pool/device/comm lane "
             "holding work whose progress counter does not move for "
             "this many ms counts watchdog.*_stalls, degrades /health "
             "and dumps a flight record. 0 = disabled", type=int)
mca.register("watchdog_p99_factor", 8.0,
             "Flight-record trigger: a histogram p99 exceeding this "
             "multiple of its EWMA baseline (with fresh samples in the "
             "window) dumps a post-mortem. <= 0 disables the breach "
             "trigger", type=float)

#: exported as ``watchdog.*`` by install_native_counters
WATCHDOG_STATS = LaneStats(
    ticks=0,
    pool_stalls=0,     # episodes, not ticks: one per continuous stall
    device_stalls=0,
    comm_stalls=0,
    clears=0,          # episodes that ended (progress resumed)
    degraded=0,        # gauge: lanes currently stalled (0 = healthy)
    peer_deaths=0,     # broken_peers transitions observed
    p99_breaches=0,    # EWMA-baseline p99 trips
    flight_dumps=0,    # dumps this module triggered
)

#: live watchdogs (weak): /health aggregates over them per probe
_live: "weakref.WeakSet[StallWatchdog]" = weakref.WeakSet()
_live_lock = threading.Lock()


def health_report() -> Optional[Dict[str, Any]]:
    """The /health degradation hook: None when no watchdog is armed,
    else ``{"degraded": bool, "stalls": [attributed...]}`` over every
    live watchdog in this process."""
    with _live_lock:
        dogs = list(_live)
    if not dogs:
        return None
    stalls: List[Dict[str, Any]] = []
    for d in dogs:
        stalls.extend(d.active_stalls())
    return {"degraded": bool(stalls), "stalls": stalls,
            "stall_ms": max(d.stall_ms for d in dogs)}


class _LaneWatch:
    """Progress tracker for one watched lane: holds the last progress
    value, when it last moved, and whether a stall episode is latched."""

    __slots__ = ("key", "kind", "progress", "since", "stalled")

    def __init__(self, key: str, kind: str) -> None:
        self.key = key
        self.kind = kind            # "pool" | "device" | "comm"
        self.progress: Optional[float] = None
        self.since = time.monotonic()
        self.stalled = False


class StallWatchdog:
    """One context's monitor thread. ``ctx`` is held weakly — a watchdog
    must never pin a finalized context alive."""

    def __init__(self, ctx, stall_ms: Optional[int] = None) -> None:
        self._ctx = weakref.ref(ctx)
        self.stall_ms = int(stall_ms if stall_ms is not None
                            else mca.get("watchdog_stall_ms", 0))
        self.rank = getattr(ctx, "my_rank", 0)
        if not self.rank:
            # a single-rank LOCAL context (the serving-tier shape) still
            # lives in a mesh process: attribute dumps to the process's
            # distributed rank when the telemetry plane knows it
            try:
                from ..comm.pttel import current_plane
                tel = current_plane()
                if tel is not None:
                    self.rank = tel.my_rank
            except Exception:  # noqa: BLE001 — attribution, not function
                pass
        self.interval_s = max(0.005, self.stall_ms / 1e3 / 4)
        self._watch: Dict[str, _LaneWatch] = {}
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._peers_broken = 0
        self._pool_error_fired = False
        self._p99_ewma: Dict[str, float] = {}
        self._p99_count: Dict[str, float] = {}
        self._p99_fired: set = set()
        try:
            from ..utils.counters import install_native_counters
            install_native_counters()
        except Exception:  # noqa: BLE001 — watch whatever is available
            pass
        with _live_lock:
            _live.add(self)

    # -------------------------------------------------------------- probes
    def active_stalls(self) -> List[Dict[str, Any]]:
        with self._mu:
            return [{"kind": w.kind, "lane": w.key,
                     "stalled_s": round(time.monotonic() - w.since, 3)}
                    for w in self._watch.values() if w.stalled]

    # --------------------------------------------------------------- rules
    def _observe(self, key: str, kind: str, held: float,
                 progress: float) -> None:
        """One lane observation: ``held`` > 0 means the lane owns work;
        ``progress`` is its monotone completion counter. A lane that
        holds work without progress past the threshold latches a stall
        episode; movement (or an emptied lane) clears it."""
        now = time.monotonic()
        with self._mu:
            w = self._watch.get(key)
            if w is None:
                w = self._watch[key] = _LaneWatch(key, kind)
        moved = w.progress is None or progress != w.progress
        w.progress = progress
        if moved or held <= 0:
            w.since = now
            if w.stalled:
                w.stalled = False
                WATCHDOG_STATS["clears"] += 1
                WATCHDOG_STATS["degraded"] = len(self.active_stalls())
                output.debug_verbose(1, "watchdog",
                                     f"{kind} lane {key} recovered")
            return
        if w.stalled or (now - w.since) * 1e3 < self.stall_ms:
            return
        w.stalled = True
        WATCHDOG_STATS[f"{kind}_stalls"] += 1
        WATCHDOG_STATS["degraded"] = len(self.active_stalls())
        detail = {"kind": kind, "lane": key, "held": held,
                  "progress": progress,
                  "stall_ms": round((now - w.since) * 1e3, 1),
                  "threshold_ms": self.stall_ms, "rank": self.rank}
        output.warning(f"watchdog: {kind} lane {key!r} stalled "
                       f"({held:g} held, no progress for "
                       f"{detail['stall_ms']:.0f}ms)")
        self._flight(f"watchdog_stall:{key}", detail)

    def _tick_pools(self, ctx) -> None:
        sp = getattr(ctx, "sched_plane", None)
        if sp is None:
            return
        with sp._lock:
            handles = dict(sp._pools)
        for h, name in handles.items():
            try:
                ps = sp.pool_stats(h)
            except Exception:  # noqa: BLE001 — freed slot mid-iteration
                continue
            if not ps.get("live"):
                continue
            self._observe(f"pool:{name}", "pool",
                          held=ps.get("queued", 0) + ps.get("inflight", 0),
                          progress=ps.get("served", 0))

    def _tick_device(self, ctx) -> None:
        lane = getattr(ctx, "_ptdev", None)
        if not lane:
            return
        try:
            s = lane.stats_cached(ttl=min(0.05, self.interval_s / 2))
        except Exception:  # noqa: BLE001 — lane mid-teardown
            return
        self._observe("ptdev", "device", held=s.get("inflight", 0),
                      progress=s.get("retired", 0))

    def _tick_comm(self, ctx) -> None:
        rde = getattr(ctx, "comm", None)
        native = getattr(rde, "native", None)
        if native is None:
            return
        try:
            s = native.comm.stats()
        except Exception:  # noqa: BLE001 — lane mid-teardown
            return
        self._observe("ptcomm", "comm", held=s.get("out_pending", 0),
                      progress=s.get("bytes_tx", 0) + s.get("acts_tx", 0))
        broken = s.get("broken_peers", 0)
        if broken > self._peers_broken:
            WATCHDOG_STATS["peer_deaths"] += broken - self._peers_broken
            self._flight(f"peer_death:{broken}",
                         {"broken_peers": broken, "rank": self.rank,
                          "comm": {k: s.get(k, 0) for k in
                                   ("out_pending", "frame_errors",
                                    "bytes_tx", "bytes_rx")}})
            self._peers_broken = broken

    def _tick_error(self, ctx) -> None:
        err = getattr(ctx, "_error", None)
        if err is not None and not self._pool_error_fired:
            self._pool_error_fired = True
            self._flight("pool_error",
                         {"error": repr(err), "rank": self.rank})

    def _tick_p99(self) -> None:
        """p99-vs-EWMA breach: per histogram, track an EWMA of p99 and a
        sample count; a p99 past ``watchdog_p99_factor`` x baseline with
        fresh samples in the window dumps once per histogram."""
        factor = mca.get("watchdog_p99_factor", 8.0)
        if factor <= 0:
            return
        try:
            from ..utils.hist import histograms
            sums = histograms.summaries()
        except Exception:  # noqa: BLE001 — advisory
            return
        for name, s in sums.items():
            p99, count = s.get("p99_us", 0.0), s.get("count", 0)
            fresh = count - self._p99_count.get(name, 0)
            self._p99_count[name] = count
            base = self._p99_ewma.get(name)
            if base is None or count < 64:
                if p99 > 0:
                    self._p99_ewma[name] = p99
                continue
            if fresh > 0 and p99 > factor * base \
                    and name not in self._p99_fired:
                self._p99_fired.add(name)
                WATCHDOG_STATS["p99_breaches"] += 1
                self._flight(f"p99_breach:{name}",
                             {"hist": name, "p99_us": p99,
                              "baseline_us": round(base, 1),
                              "factor": factor, "rank": self.rank})
            # slow EWMA: the baseline must not chase the breach
            self._p99_ewma[name] = 0.9 * base + 0.1 * p99

    def _flight(self, key: str, detail: Dict[str, Any]) -> None:
        try:
            from ..tools.flight import record
            if record(key.split(":", 1)[0], detail, key=key,
                      ctx=self._ctx()) is not None:
                WATCHDOG_STATS["flight_dumps"] += 1
        except Exception as e:  # noqa: BLE001 — the dump is best-effort
            output.debug_verbose(1, "watchdog", f"flight dump failed: {e}")

    # ----------------------------------------------------------- lifecycle
    def tick(self) -> None:
        """One monitoring pass (also callable directly from tests)."""
        ctx = self._ctx()
        if ctx is None:
            return
        WATCHDOG_STATS["ticks"] += 1
        self._tick_pools(ctx)
        self._tick_device(ctx)
        self._tick_comm(ctx)
        self._tick_error(ctx)
        self._tick_p99()

    def start(self) -> "StallWatchdog":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="parsec-tpu-watchdog")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — monitoring is advisory
                output.debug_verbose(1, "watchdog", f"tick failed: {e}")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        with self._mu:
            live = sum(1 for w in self._watch.values() if w.stalled)
            self._watch.clear()
        if live:
            WATCHDOG_STATS["clears"] += live
        with _live_lock:
            _live.discard(self)
        WATCHDOG_STATS["degraded"] = 0 if not _live else \
            sum(len(d.active_stalls()) for d in _live)
