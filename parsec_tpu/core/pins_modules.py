"""PINS instrumentation modules.

Re-design of the reference's module set (parsec/mca/pins/*):

* :class:`TaskProfiler` — feeds the profiling trace from task lifecycle
  events (ref: pins/task_profiler).
* :class:`PrintSteals` — per-stream steal accounting (ref: pins/print_steals;
  "distance" > 0 on select means the task came from another stream's queue).
* :class:`IteratorsChecker` — walks every executed task's successor
  descriptors and validates them against the dependency engine — the
  runtime "race detector" for DSL-generated dataflow
  (ref: pins/iterators_checker).
* :class:`ALPerf` — accumulated-lifecycle performance counters
  (ref: pins/alperf): tasks scheduled/executed/completed per second.
* :class:`PTGToDTD` — replays a PTG taskpool through the DTD frontend, the
  cross-DSL test harness (ref: pins/ptg_to_dtd) — see
  :func:`ptg_to_dtd_replay`.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

from ..utils import output
from . import pins as P
from .task import FLOW_ACCESS_CTL, Task


class PinsModule:
    name = "base"

    def enable(self, context) -> None:
        self.context = context
        self._register(context.pins)

    def disable(self, context) -> None:
        self._unregister(context.pins)

    def _register(self, pins) -> None:
        raise NotImplementedError

    def _unregister(self, pins) -> None:
        pass


class TaskProfiler(PinsModule):
    """Emit exec/schedule/complete events into the profiling trace."""

    name = "task_profiler"

    def __init__(self, profiling) -> None:
        self.prof = profiling
        self.keys: Dict[str, tuple] = {}
        self._streams: Dict[int, Any] = {}
        self._lock = threading.Lock()

    def _stream_for(self, stream) -> Any:
        sid = getattr(stream, "th_id", -1)
        s = self._streams.get(sid)
        if s is None:
            with self._lock:
                s = self._streams.get(sid)
                if s is None:
                    s = self.prof.stream(f"es{sid}")
                    self._streams[sid] = s
        return s

    def _key(self, task: Task, end: bool) -> int:
        name = task.task_class.name
        ks = self.keys.get(name)
        if ks is None:
            ks = self.prof.add_dictionary_keyword(name, info_desc="prio{i}")
            self.keys[name] = ks
        return ks[1] if end else ks[0]

    def _register(self, pins) -> None:
        pins.register(P.EXEC_BEGIN, self._exec_begin)
        pins.register(P.EXEC_END, self._exec_end)
        pins.register(P.COMPLETE_EXEC_END, self._complete)

    def _unregister(self, pins) -> None:
        pins.unregister(P.EXEC_BEGIN, self._exec_begin)
        pins.unregister(P.EXEC_END, self._exec_end)
        pins.unregister(P.COMPLETE_EXEC_END, self._complete)

    def _eid(self, task: Task) -> int:
        return hash(task.key) & 0x7FFFFFFF

    def _exec_begin(self, stream, task, extra) -> None:
        from ..utils.trace import EVENT_FLAG_START
        key = self._key(task, False)   # registers the keyword on first use
        info = self.prof.pack_info(task.task_class.name, prio=task.priority)
        self._stream_for(stream).trace(key, self._eid(task),
                                       task.taskpool.taskpool_id,
                                       EVENT_FLAG_START, info)

    def _exec_end(self, stream, task, extra) -> None:
        from ..utils.trace import EVENT_FLAG_END
        self._stream_for(stream).trace(self._key(task, True), self._eid(task),
                                       task.taskpool.taskpool_id,
                                       EVENT_FLAG_END)

    def _complete(self, stream, task, extra) -> None:
        pass


class PrintSteals(PinsModule):
    """Count work steals per stream (ref: pins/print_steals)."""

    name = "print_steals"

    def __init__(self) -> None:
        self.steals: Dict[int, int] = defaultdict(int)
        self.selects: Dict[int, int] = defaultdict(int)

    def _register(self, pins) -> None:
        pins.register(P.SELECT_END, self._select_end)

    def _unregister(self, pins) -> None:
        pins.unregister(P.SELECT_END, self._select_end)

    def _select_end(self, stream, task, extra) -> None:
        if task is None:
            return
        self.selects[stream.th_id] += 1

    def report(self) -> Dict[int, Dict[str, int]]:
        return {tid: {"selects": n, "steals": self.steals[tid]}
                for tid, n in self.selects.items()}


class IteratorsChecker(PinsModule):
    """Validate DSL-generated successor descriptors at runtime.

    For every completed task, re-walks its out-deps and checks each
    successor's locals are inside the peer's declared ranges and that the
    dep targets an existing flow — catching miscompiled dataflow the way the
    reference's iterators_checker does.
    """

    name = "iterators_checker"

    def __init__(self) -> None:
        self.violations: List[str] = []

    def _register(self, pins) -> None:
        pins.register(P.COMPLETE_EXEC_BEGIN, self._check)

    def _unregister(self, pins) -> None:
        pins.unregister(P.COMPLETE_EXEC_BEGIN, self._check)

    def _check(self, stream, task: Task, extra) -> None:
        tc = task.task_class
        for flow in tc.flows:
            for dep in flow.deps_out:
                if dep.task_class is None:
                    continue
                if dep.cond is not None and not dep.cond(task.locals):
                    continue
                try:
                    targets = dep.target_locals(task.locals) if dep.target_locals \
                        else [task.locals]
                except Exception as e:  # noqa: BLE001
                    self.violations.append(
                        f"{task!r}.{flow.name}: target_locals raised {e!r}")
                    continue
                if isinstance(targets, dict):
                    targets = [targets]
                peer = dep.task_class
                for tl in targets:
                    if dep.flow_index >= len(peer.flows):
                        self.violations.append(
                            f"{task!r}.{flow.name}: dep to missing flow "
                            f"#{dep.flow_index} of {peer.name}")
                    ranges = getattr(peer, "_ptg_ranges", None)
                    if ranges:
                        env = dict(getattr(task.taskpool, "env_base", {}))
                        for param, lo, hi, _st in ranges:
                            env.update(tl)
                            v = tl.get(param)
                            if v is None:
                                continue
                            if not (int(lo(env)) <= v <= int(hi(env))):
                                self.violations.append(
                                    f"{task!r}.{flow.name} -> {peer.name}{tl}: "
                                    f"{param}={v} outside range")


class ALPerf(PinsModule):
    """Accumulated lifecycle rates (ref: pins/alperf)."""

    name = "alperf"

    def __init__(self) -> None:
        self.t0 = time.perf_counter()
        self.counts: Dict[str, int] = defaultdict(int)

    def _register(self, pins) -> None:
        pins.register(P.SCHEDULE_END, lambda s, t, e: self._bump("scheduled", t))
        pins.register(P.EXEC_END, lambda s, t, e: self._bump("executed", t))
        pins.register(P.COMPLETE_EXEC_END, lambda s, t, e: self._bump("completed", t))

    def _bump(self, what: str, t) -> None:
        n = len(t) if isinstance(t, list) else 1
        self.counts[what] += n

    def report(self) -> Dict[str, float]:
        dt = max(time.perf_counter() - self.t0, 1e-9)
        r = {k: v / dt for k, v in self.counts.items()}
        r["elapsed_s"] = dt
        return r


class HWCounters(PinsModule):
    """Hardware PMU counters around task execution (the PAPI role, ref:
    parsec/mca/pins/papi/ — mod_papi.c samples counters at EXEC begin/end
    through libpapi; here raw perf_event_open, utils/perf_event.py).

    Accumulates per-task-class deltas (cycles, instructions, ...); a host
    where perf_event is unavailable (seccomp, paranoid level, no PMU)
    yields a module that enables as a NO-OP — same shape as the reference
    only building pins/papi when libpapi exists."""

    name = "hw_counters"

    def __init__(self, events=("cycles", "instructions")) -> None:
        from ..utils import perf_event
        self._pe = perf_event
        self.events = tuple(events)
        self.active = perf_event.available()
        self._hw = None
        self._pending: Dict[int, Dict[str, int]] = {}
        self.per_class: Dict[str, Dict[str, int]] = defaultdict(
            lambda: defaultdict(int))
        self.tasks_sampled = 0

    def _register(self, pins) -> None:
        if not self.active:
            output.debug_verbose(
                1, "pins", "hw_counters: perf_event unavailable; no-op")
            return
        self._hw = self._pe.HWCounterSet(self.events)
        self._hw.start()
        pins.register(P.EXEC_BEGIN, self._on_begin)
        pins.register(P.EXEC_END, self._on_end)

    def _unregister(self, pins) -> None:
        if not self.active:
            return
        pins.unregister(P.EXEC_BEGIN, self._on_begin)
        pins.unregister(P.EXEC_END, self._on_end)
        if self._hw is not None:
            self._hw.close()
            self._hw = None

    def _on_begin(self, stream, task, extra) -> None:
        self._pending[id(task)] = self._hw.read()

    def _on_end(self, stream, task, extra) -> None:
        t0 = self._pending.pop(id(task), None)
        if t0 is None:
            return
        t1 = self._hw.read()
        acc = self.per_class[task.task_class.name]
        for k in self.events:
            acc[k] += t1[k] - t0[k]
        self.tasks_sampled += 1

    def report(self) -> Dict[str, Dict[str, int]]:
        return {cls: dict(v) for cls, v in self.per_class.items()}


def ptg_to_dtd_replay(ptg_taskpool, ctx, name: Optional[str] = None,
                      capture: bool = False):
    """Replay a PTG taskpool's task space through the DTD frontend.

    The cross-DSL harness (ref: pins/ptg_to_dtd): enumerate the PTG task
    space, and for each task insert a DTD task touching the same memory
    endpoints with the same access modes. Dataflow through repos becomes
    dataflow through tiles; results must match the PTG execution.
    Returns the DTD taskpool (caller waits/closes).

    Anonymous task→task flows ride per-flow scratch tiles keyed by the
    PRODUCER (class, key, flow); memory out-deps copy home (the replay
    analogue of PTG's complete-execution write-back).

    With ``capture=True`` the replay lands in a captured pool
    (dsl/capture.py): a PTG program — a static task space by definition —
    compiles into ONE XLA executable. PTG bodies are jitted already, so
    the replay wrappers trace through.
    """
    from ..dsl.dtd import DTDTaskpool, READ, RW, WRITE
    from ..dsl.ptg.compiler import PTGTaskpool, _payload_of
    assert isinstance(ptg_taskpool, PTGTaskpool)
    tp = DTDTaskpool(ctx, name or f"{ptg_taskpool.name}-dtd", capture=capture)
    spec = ptg_taskpool.program.spec

    scratch: Dict[Any, Any] = {}

    def scratch_tile(cls_name: str, key: tuple, flow: str):
        k = (cls_name, key, flow)
        t = scratch.get(k)
        if t is None:
            t = tp.tile_new((1,))
            scratch[k] = t
        return t

    for tc, loc in ptg_taskpool._enumerate():
        tcs = tc._ptg_spec
        env = ptg_taskpool._env(loc)
        args = []
        accesses = []
        for fi, fs in enumerate(tcs.flows):
            if fs.access == "CTL":
                continue
            acc = {"READ": READ, "WRITE": WRITE, "RW": RW}[fs.access]
            ep = tc._ptg_active_in(tc._ptg_in_specs[fi], env)
            if ep is not None and ep["kind"] == "memory":
                dc = ptg_taskpool.collections[ep["name"]]
                tile = tp.tile_of(dc, *[ex.values(env)[0] for ex in ep["exprs"]])
            elif ep is not None and ep["kind"] == "task":
                pkey = tuple(ex.values(env)[0] for ex in ep["exprs"])
                tile = scratch_tile(ep["name"], pkey, ep["flow"])
            else:
                tile = scratch_tile(tcs.name, tuple(loc.values()), fs.name)
            # writes also publish into this task's own scratch/memory targets
            args.append((tile, acc))
            accesses.append(acc)
        params = [loc[p] for p in tcs.params]
        # reuse the PTG-compiled body through a DTD-shaped wrapper
        fn = _dtd_wrapper_for(ptg_taskpool, tcs, tc)
        tp.insert_task(fn, *args, *params, name=f"{tcs.name}-replay",
                       jit=capture)
        # route written outputs onward: memory out-deps write home like PTG;
        # task out-deps land in the successor's scratch tile
        _route_outputs(ptg_taskpool, tp, tc, tcs, loc, env, args, scratch_tile)
    return tp


def _dtd_wrapper_for(ptp, tcs, tc):
    data_flows = [f for f in tcs.flows if f.access != "CTL"]
    chore_fn = tc._ptg_body_fn

    def wrapper(*vals):
        nflows = len(data_flows)
        tiles = vals[:nflows]
        params = vals[nflows:]
        outs = chore_fn(*params, *tiles)
        return outs
    wrapper.__name__ = f"{tcs.name}_replay"
    return wrapper


def _replay_copy(d_, s_):
    return s_


def _route_outputs(ptp, tp, tc, tcs, loc, env, args, scratch_tile) -> None:
    """After the replayed task, publish its written flows where successor
    replays will read them. Scratch tiles are keyed by the PRODUCER
    (class, key, flow) — the key a consumer's input endpoint names
    ("C GEMM(m,n,k-1)" reads scratch(GEMM, (m,n,k-1), C)) — and memory
    out-deps copy home, the replay analogue of PTG's complete-execution
    write-back."""
    import itertools

    from ..dsl.dtd import READ, RW
    from ..dsl.ptg.compiler import _index_expr
    flow_tiles = {}
    di = 0
    for fs in tcs.flows:
        if fs.access == "CTL":
            continue
        flow_tiles[fs.name] = args[di][0]
        di += 1
    jit_copy = getattr(tp, "_capture", None) is not None
    for fs in tcs.flows:
        if fs.access not in ("WRITE", "RW"):
            continue
        src = flow_tiles[fs.name]
        has_task_out = False
        for d in fs.deps:
            if d.direction != "out":
                continue
            for ep, neg in ((d.endpoint, False), (d.else_endpoint, True)):
                if ep is None:
                    continue
                if d.guard is not None:
                    v = bool(eval(compile(d.guard, "<g>", "eval"), dict(env)))  # noqa: S307
                    if neg:
                        v = not v
                    if not v:
                        continue
                if ep.kind == "task":
                    has_task_out = True
                elif ep.kind == "memory":
                    exprs = [_index_expr(e) for e in ep.index_exprs]
                    for combo in itertools.product(
                            *[ex.values(env) for ex in exprs]):
                        dc = ptp.collections[ep.name]
                        dst = tp.tile_of(dc, *combo)
                        if dst is not src:
                            tp.insert_task(_replay_copy, (dst, RW),
                                           (src, READ), name="replay-copy",
                                           jit=jit_copy)
        if has_task_out:
            # one producer-keyed publication serves every consumer
            dst = scratch_tile(tcs.name, tuple(loc.values()), fs.name)
            if dst is not src:
                tp.insert_task(_replay_copy, (dst, RW), (src, READ),
                               name="replay-copy", jit=jit_copy)
