"""Data repositories: produced copies held for successors.

Re-design of parsec/datarepo.{c,h}. One repo per task class per taskpool; each
entry is keyed by the producing task's key and holds the data copies it
produced, one slot per flow. The retire protocol mirrors the reference
(datarepo.h:74-90): an entry carries ``usagelmt`` (how many successor uses will
happen) and ``usagecnt`` (how many happened); when they meet, the entry retires
and its copies drop a reference.

Native-lane contract: PTG taskpools that the native execution lane accepts
(docs/native_exec.md) never touch these repos — the SAME usagelmt/usagecnt
protocol runs over the lane's per-task slot array inside
``native/src/ptexec.cpp`` (``usagelmt`` = the flatten's consumer count per
slot, the retire moment = the slot-clear in the batched callback), and
``Graph.slot_stats()`` reports the lane-side retire counters. The parity
harness checks both sides leave ZERO live entries at pool completion; the
``retired`` counter below exists so that check can also see that retires
actually happened on the Python side.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional


class DataRepoEntry:
    """Ref: data_repo_entry_t (parsec/datarepo.h:74-90)."""

    __slots__ = ("key", "data", "usagelmt", "usagecnt", "retained", "_repo",
                 "_mp_owner")

    def __init__(self, repo: "DataRepo", key: Any, nb_flows: int) -> None:
        self.key = key
        self.data: List[Any] = [None] * nb_flows  # DataCopy per flow
        self.usagelmt = 0
        self.usagecnt = 0
        self.retained = 0
        self._repo = repo


class DataRepo:
    """Hash table of repo entries for one task class (ref: datarepo.c).

    Entries come from a thread-affine :class:`~parsec_tpu.utils.mempool.
    Mempool` — the reference allocates repo entries from parsec_mempool_t
    for exactly this churn profile (one entry per produced task, retired
    when all successors consumed)."""

    def __init__(self, nb_flows: int, name: str = "") -> None:
        self.nb_flows = nb_flows
        self.name = name
        self.retired = 0          # entries fully consumed and released
        self._table: Dict[Any, DataRepoEntry] = {}
        self._lock = threading.Lock()
        from ..utils.mempool import Mempool
        self._pool = Mempool(
            factory=lambda: DataRepoEntry(self, None, nb_flows),
            reset=self._scrub)

    def _scrub(self, e: DataRepoEntry) -> None:
        e.key = None
        for i in range(self.nb_flows):
            e.data[i] = None
        e.usagelmt = 0
        e.usagecnt = 0
        e.retained = 0

    def lookup_entry(self, key: Any) -> Optional[DataRepoEntry]:
        with self._lock:
            return self._table.get(key)

    def lookup_entry_and_create(self, key: Any) -> DataRepoEntry:
        """data_repo_lookup_entry_and_create: get-or-insert, retained."""
        with self._lock:
            e = self._table.get(key)
            if e is None:
                e = self._pool.alloc()
                e.key = key
                self._table[key] = e
            e.retained += 1
            return e

    def entry_used_once(self, key: Any) -> None:
        """data_repo_entry_used_once: one successor consumed its input."""
        retire = None
        with self._lock:
            e = self._table.get(key)
            if e is None:
                return
            e.usagecnt += 1
            if e.usagelmt and e.usagecnt >= e.usagelmt and e.retained == 0:
                retire = self._table.pop(key, None)
                if retire is not None:
                    self.retired += 1
        if retire is not None:
            self._release(retire)

    def entry_addto_usage_limit(self, key: Any, lmt: int) -> None:
        """data_repo_entry_addto_usage_limit + release of the creator's retain."""
        retire = None
        with self._lock:
            e = self._table.get(key)
            if e is None:
                return
            e.usagelmt += lmt
            e.retained = max(0, e.retained - 1)
            if e.usagelmt and e.usagecnt >= e.usagelmt and e.retained == 0:
                retire = self._table.pop(key, None)
                if retire is not None:
                    self.retired += 1
        if retire is not None:
            self._release(retire)

    def _release(self, entry: DataRepoEntry) -> None:
        for copy in entry.data:
            if copy is not None and hasattr(copy, "release"):
                copy.release()
        # mempool return AFTER the copies dropped their references: the
        # scrub clears the slots, and the shell re-enters circulation
        self._pool.release(entry)

    def pool_stats(self) -> Dict[str, int]:
        st = self._pool.stats()
        st["retired"] = self.retired
        return st

    def __len__(self) -> int:
        return len(self._table)
