"""Runtime context, execution streams, and the scheduling state machine.

Re-design of parsec/parsec.c (parsec_init, :405) + parsec/scheduling.c:

* :class:`ExecutionStream` — one per worker thread (ref:
  parsec_execution_stream_t, parsec/include/parsec/execution_stream.h:36-76).
* :class:`Context` — process-wide state (ref: parsec_context_t,
  execution_stream.h:117-174), with ``add_taskpool / start / wait / test``
  mirroring parsec/runtime.h:174-388.
* The per-thread hot loop re-creates ``__parsec_context_wait``
  (scheduling.c:727, hot loop :789-818) including exponential backoff and
  master-thread communication progress.
* ``_task_progress`` re-creates ``__parsec_task_progress`` (scheduling.c:507)
  and ``__parsec_execute`` (scheduling.c:126): prepare_input → best-device
  selection → chore evaluate/hook → return-code dispatch
  (DONE/AGAIN/ASYNC/NEXT/DISABLE, scheduling.c:518-566).
* ``generic_release_deps`` re-creates the dependency-release engine
  (parsec_release_dep_fct parsec.c:1837, parsec_release_local_OUT_dependencies
  parsec.c:1750, parsec_update_deps_with_mask parsec.c:1657).

TPU-first deviation: device chores dispatch pre-compiled XLA/Pallas
executables asynchronously and return ``HOOK_ASYNC``; the progress loop polls
device modules (the analogue of the reference's GPU manager thread,
device_gpu.c:3376+) so a single host thread can keep the chip saturated —
important because host cores are scarce relative to TPU throughput.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..utils import mca, output
from . import pins as pins_mod
from . import scheduler as sched_mod
from . import termdet as termdet_mod
from .datarepo import DataRepo
from .task import (
    DEV_ALL, DEV_CPU, FLOW_ACCESS_CTL, FLOW_ACCESS_WRITE,
    HOOK_AGAIN, HOOK_ASYNC, HOOK_DISABLE, HOOK_DONE, HOOK_ERROR, HOOK_NEXT,
    Task, TaskClass, Taskpool,
    TASK_STATUS_COMPLETE, TASK_STATUS_HOOK, TASK_STATUS_PREPARE_INPUT,
)

mca.register("runtime_nb_cores", 0, "Worker threads (0 = autodetect)", type=int)
mca.register("runtime_backoff_max_us", 1000, "Max starvation backoff (µs)", type=int)
mca.register("runtime_gc_defer", True,
             "Stretch Python cyclic-GC thresholds while taskpools are in "
             "flight (the mempool discipline of the reference: no "
             "allocator churn in the hot path). Task/tile graphs are "
             "cyclic and mostly LIVE mid-DAG, so frequent young-gen scans "
             "only promote them and full collections walk the whole heap "
             "— measured ~2x EP task throughput. Fully disabling GC "
             "instead would leak jax buffer cycles and force a costly "
             "whole-heap collect at quiescence (measured 3x on tiled "
             "POTRF), so thresholds are stretched, not switched off",
             type=bool)
mca.register("debug_paranoid", 0,
             "Assertion tier (ref: PARSEC_DEBUG_PARANOID): >0 adds runtime "
             "invariant checks in the scheduling hot path (not-ready or "
             "completed tasks entering the queues, double completion)",
             type=int)


# process-wide refcount for the GC-stretch window (several rank contexts
# can live in one process; gc thresholds are global)
_gc_defer_lock = threading.Lock()
_gc_defer_count = 0
_gc_saved_thresholds = None
_GC_STRETCHED = (50_000, 20, 20)    # vs the (700, 10, 10) default


def _gc_defer_acquire() -> None:
    global _gc_defer_count, _gc_saved_thresholds
    import gc
    with _gc_defer_lock:
        _gc_defer_count += 1
        if _gc_defer_count == 1:
            _gc_saved_thresholds = gc.get_threshold()
            gc.set_threshold(*_GC_STRETCHED)


def _gc_defer_release() -> None:
    global _gc_defer_count, _gc_saved_thresholds
    import gc
    with _gc_defer_lock:
        if _gc_defer_count == 0:
            return
        _gc_defer_count -= 1
        if _gc_defer_count == 0 and _gc_saved_thresholds is not None:
            gc.set_threshold(*_gc_saved_thresholds)
            _gc_saved_thresholds = None


class ExecutionStream:
    """One worker's view of the runtime (ref: execution_stream.h:36-76)."""

    __slots__ = ("th_id", "vp_id", "context", "next_task", "nb_selects",
                 "nb_executed", "prof", "rng_state")

    def __init__(self, th_id: int, context: "Context", vp_id: int = 0) -> None:
        self.th_id = th_id
        self.vp_id = vp_id
        self.context = context
        self.next_task: Optional[Task] = None   # es->next_task locality slot
        self.nb_selects = 0
        self.nb_executed = 0
        self.prof = None
        self.rng_state = (th_id * 2654435761) & 0xFFFFFFFF

    @property
    def is_master(self) -> bool:
        return self.th_id == 0  # ref: PARSEC_THREAD_IS_MASTER


class Context:
    """Process-wide runtime (ref: parsec_context_t + parsec_init parsec.c:405)."""

    def __init__(
        self,
        nb_cores: Optional[int] = None,
        scheduler: Optional[str] = None,
        argv: Optional[List[str]] = None,
        my_rank: int = 0,
        nb_ranks: int = 1,
    ) -> None:
        if argv:
            mca.parse_cmdline(argv)
        if nb_cores is None:
            nb_cores = mca.get("runtime_nb_cores", 0) or (os.cpu_count() or 1)
        self.nb_cores = max(1, nb_cores)
        self.my_rank = my_rank
        self.nb_ranks = nb_ranks
        self.pins = pins_mod.PinsManager()
        self.paranoid = mca.get("debug_paranoid", 0)
        from .vpmap import VPMap
        self.vpmap = VPMap(nb_threads=self.nb_cores)
        self.streams: List[ExecutionStream] = [
            ExecutionStream(i, self, vp_id=self.vpmap.thread_to_vp(i))
            for i in range(self.nb_cores)
        ]
        #: True when the user picked a scheduler policy explicitly (ctor
        #: arg or --mca sched): execution-order policy then matters to
        #: them, and order-bypassing fast lanes (the DTD batched drain,
        #: which backfills outside the scheduler queues) must not engage
        self.sched_explicit = scheduler is not None or \
            mca.get("sched", "lfq") != "lfq"
        self.sched = sched_mod.create(scheduler)
        self.sched.install(self)
        for s in self.streams:
            self.sched.flow_init(s)
        #: native multi-pool scheduler plane (core/sched_plane.py, ISSUE
        #: 9): the shared ready plane the ptexec/ptdtd lanes drain
        #: through — per-worker hot queues, work stealing, weighted DRR
        #: across taskpools, admission windows. None when --mca
        #: sched_native 0, the native module is missing, or the selected
        #: scheduler policy has no native flavor (counted fallback)
        from .sched_plane import SchedPlane
        self.sched_plane = SchedPlane.maybe_create(self)
        # device registry (lazy import to avoid cycles)
        from ..device.device import DeviceRegistry
        self.devices = DeviceRegistry(self)
        self.comm = None            # set by parsec_tpu.comm when distributed
        #: process tracer: attach one directly (``ctx.profiling =
        #: Profiling()``) or let ``--mca profile_enabled 1`` create it —
        #: mca-created tracers dump to ``--mca profile_filename`` at fini
        #: (the reference's parsec_fini dbp write)
        self.profiling = None
        self._prof_auto = False
        if mca.get("profile_enabled", False):
            from ..utils.trace import Profiling
            self.profiling = Profiling()
            self._prof_auto = True
        self._taskpools: Dict[int, Taskpool] = {}
        self._active = 0
        self._cv = threading.Condition()
        self._started = False
        self._finalized = False
        self._workers: List[threading.Thread] = []
        self._work_event = threading.Event()
        self._error: Optional[BaseException] = None
        self._prio_seen = False   # any nonzero-priority task ever scheduled
        #: weak bound-method refs invoked when a progress loop starts or
        #: starves — producers holding amortization buffers (the DTD ready
        #: batch) drain here so direct _progress_loop users see their
        #: tasks. WEAK on purpose: a dropped taskpool must not be pinned
        #: alive (or keep costing a call per starved iteration) just
        #: because it once registered a hook
        self._drain_hooks: List = []
        # per-thread stream binding (was a thread-NAME parse on every
        # schedule() — the single hottest line of the EP profile)
        self._tls = threading.local()
        self._tls.stream = self.streams[0]
        #: serializes progress loops on the MASTER stream: every
        #: non-worker thread (wait()/wait_taskpool()/fini drain/DTD
        #: window stall/direct _progress_loop users) drives streams[0],
        #: and two concurrent drivers race on streams[0].next_task (the
        #: read-then-clear hand-off can execute a task twice or drop it).
        #: REENTRANT: nested loops on one thread (wait inside a drain)
        #: are legal
        self._master_loop_lock = threading.RLock()
        # schedule() only needs to wake anyone when parked workers or a
        # comm thread exist; single-core local runs skip the Event syscall
        # (RemoteDepEngine flips this when it attaches)
        self._need_wake = self.nb_cores > 1
        self._gc_held = False
        #: native PTG execution lanes awaiting drain: [(taskpool, lane)].
        #: Every stream's hot loop joins the front graph's run() (the C
        #: walk is GIL-free, so in-process workers scale on real cores)
        self._ptexec_q: List = []
        self._ptexec_lock = threading.Lock()
        #: the native DEVICE lane (device/native.py, ISSUE 10): one per
        #: context, created lazily the first time a TPU-bodied pool
        #: prepares for the execution lane (None = not yet tried, False =
        #: tried and unavailable). Its manager thread feeds completions
        #: back into the graphs GIL-free; fini tears it down BEFORE the
        #: device modules.
        self._ptdev: Any = None
        #: count of DEVICE-BOUND lane graphs in flight — same backoff
        #: treatment as comm-bound graphs: the next ready task comes from
        #: the lane's manager thread, not from this process's walk
        self._ptexec_dev_live = 0
        #: count of COMM-BOUND lane graphs in flight: while one lives,
        #: starvation backoff is capped near the wire latency — the comm
        #: progress thread ingests remote releases GIL-free at any
        #: moment, and a millisecond-scale sleep between lane polls would
        #: put the hot loop (not the wire) on the critical path of every
        #: cross-rank dependency chain
        self._ptexec_comm_live = 0
        #: the per-context native DTD engine (set by DTDTaskpool) and the
        #: count of LIVE batched-lane pools: while any pool has the
        #: batched insert lane armed, every stream's hot loop drains the
        #: engine's internal ready structure (drain_ready) the way it
        #: drains ptexec graphs. A count, not a sticky flag: each pool's
        #: final completion decrements it, so later non-batch pools (e.g.
        #: the bench's per-task-engine baseline reps) don't pay an empty
        #: engine drain every idle iteration
        self._dtd_neng = None
        self._dtd_batch_pools = 0
        #: bridge landing the native lanes' in-lane ring events into
        #: self.profiling (utils/native_trace.py); created lazily when a
        #: lane arms while profiling is attached — zero cost otherwise
        self._ntrace = None
        #: per-rank metrics endpoint (tools/metrics_server.py): the
        #: counter registry + latency percentiles over HTTP/UDS JSON,
        #: up for the context's whole life (--mca metrics_port / _uds)
        from ..tools.metrics_server import MetricsServer
        self.metrics = MetricsServer.maybe_start(my_rank, nb_ranks)
        #: native latency histograms (utils/hist.py): armed on every
        #: lane the context enqueues when requested explicitly or
        #: implied by a live metrics endpoint (/metrics serves live
        #: percentiles); off = one null branch per lane event site
        self._hist_on = bool(mca.get("hist_enabled", False)) or \
            self.metrics is not None
        #: lane stall watchdog (core/watchdog.py): armed by --mca
        #: watchdog_stall_ms; reads existing counters only (the PR 13
        #: no-new-hot-path contract), degrades /health on a latched
        #: stall and triggers the flight recorder
        self.watchdog = None
        wd_ms = mca.get("watchdog_stall_ms", 0)
        if wd_ms > 0:
            from .watchdog import StallWatchdog
            self.watchdog = StallWatchdog(self, stall_ms=wd_ms).start()
        if self.sched_plane is not None:
            # sched.queue_ns (push->pop wait) joins the lane histograms
            self._hist_attach("sched", self.sched_plane.plane)
        output.debug_verbose(2, "runtime",
                             f"context up: {self.nb_cores} streams, sched={self.sched.name}")

    # ------------------------------------------------------- in-lane tracing
    def _native_trace(self):
        """The native-lane trace bridge, or None when neither profiling
        (``ctx.profiling``, set by tests/users or --mca profile_enabled)
        nor PINS instrumentation is active. With PINS but no tracer the
        bridge runs marker-only (coarse NativeDrainMarker events, nothing
        landed) so instrumented pools can stay on the native lanes
        without PINS consumers seeing a silent, idle machine. Lazily
        constructed and registered as a drain hook so starving progress
        loops land pending ring events."""
        prof = self.profiling
        if prof is not None and not getattr(prof, "enabled", True):
            prof = None
        if prof is None and not self.pins.enabled:
            return None
        if self._ntrace is None:
            from ..utils.native_trace import NativeTraceBridge
            self._ntrace = NativeTraceBridge(prof, self.pins)
            self.register_drain_hook(self._ntrace.drain_all)
        elif self._ntrace.prof is None and prof is not None:
            # a tracer attached after a marker-only bridge armed: upgrade
            self._ntrace.prof = prof
        return self._ntrace

    def _ntrace_attach(self, kind: str, obj, tpid: int = 0) -> None:
        nt = self._native_trace()
        if nt is not None:
            nt.attach(kind, obj, tpid)

    def _ntrace_detach(self, obj) -> None:
        if self._ntrace is not None:
            self._ntrace.detach(obj)

    # --------------------------------------------------- latency histograms
    def _hist_attach(self, kind: str, obj) -> None:
        """Arm ``obj``'s native latency histograms (pthist.h) when the
        context wants them; called from the same lifecycle points as
        :meth:`_ntrace_attach`."""
        if self._hist_on:
            from ..utils.hist import histograms
            histograms.attach(kind, obj)

    def _hist_detach(self, obj) -> None:
        """Fold a finishing lane object's buckets into the process
        accumulator so /metrics keeps reporting completed pools."""
        if self._hist_on:
            from ..utils.hist import histograms
            histograms.detach(obj)

    # ----------------------------------------------------- online cost model
    def _cost_fold(self, lane: Dict[str, Any]) -> None:
        """Fold a finishing lane's cost observations into the online cost
        model (ISSUE 18) — the SAME lifecycle moment as the histogram
        registry's detach, and idempotent the same way the abandon path
        needs: every exiting stream of an errored graph attempts this,
        the pop()s make only the first one fold."""
        meta = lane.pop("cost_meta", None)
        obs = lane.pop("cost_dev", None)
        if meta is None and not obs:
            return
        from .costmodel import fold_cost_rows, model
        if meta is not None:
            try:
                fold_cost_rows(meta, lane["graph"].cost_snapshot())
            except Exception:  # noqa: BLE001 — folding is advisory
                pass
        if obs:
            # the device lane's dispatch/poll observations (manager-thread
            # local dict: (cls, bucket, dev) -> [count, sum_ns])
            model.fold_pairs((k, v[0], v[1]) for k, v in obs.items())

    def register_drain_hook(self, bound_method) -> None:
        import weakref
        self._drain_hooks.append(weakref.WeakMethod(bound_method))

    def unregister_drain_hook(self, bound_method) -> None:
        self._drain_hooks = [r for r in self._drain_hooks
                             if r() is not None and r() != bound_method]

    def _run_drain_hooks(self) -> None:
        dead = False
        for ref in tuple(self._drain_hooks):
            fn = ref()
            if fn is None:
                dead = True
                continue
            fn()
        if dead:
            self._drain_hooks = [r for r in self._drain_hooks
                                 if r() is not None]

    # ------------------------------------------------------------------ setup
    def add_taskpool(self, tp: Taskpool) -> None:
        """parsec_context_add_taskpool (ref: scheduling.c:865-923)."""
        if self._finalized:
            output.fatal("context already finalized")
        tp.context = self
        if tp.termdet is None:
            termdet_mod.LocalTermdet().monitor_taskpool(tp)  # ref: scheduling.c:879-884
        with self._cv:
            self._taskpools[tp.taskpool_id] = tp
            self._active += 1
            first = self._active == 1
        if first and mca.get("runtime_gc_defer", True):
            # the hold + finalizer transition under _cv: racing a
            # concurrent quiesce-release outside the lock could detach the
            # WRONG finalizer and lose the crash-safety net
            with self._cv:
                if not self._gc_held:
                    self._gc_held = True
                    _gc_defer_acquire()
                    # crash-safety (VERDICT r4 weak #6): a context
                    # abandoned without fini() must not leave process-wide
                    # GC thresholds stretched forever — the finalizer
                    # releases this context's hold when it is collected
                    import weakref
                    self._gc_finalizer = weakref.finalize(
                        self, _gc_defer_release)
        # taskpool keeps one pending action for the enqueue itself
        tp.addto_nb_pending_actions(1)
        if tp.on_enqueue is not None:
            tp.on_enqueue(tp)
        if tp.startup_hook is not None:
            startup = tp.startup_hook(self.streams[0], tp)
            if startup:
                self.schedule(startup, self.streams[0])
        tp.termdet.taskpool_ready(tp)
        tp.addto_nb_pending_actions(-1)
        self._work_event.set()

    def _taskpool_completed(self, tp: Taskpool) -> None:
        with self._cv:
            if tp.taskpool_id in self._taskpools:
                del self._taskpools[tp.taskpool_id]
                self._active -= 1
            quiesced = self._active == 0
            self._cv.notify_all()
        if quiesced:
            self._release_gc_hold()

    def _release_gc_hold(self) -> None:
        with self._cv:
            if not self._gc_held:
                return
            self._gc_held = False
            fin = getattr(self, "_gc_finalizer", None)
            self._gc_finalizer = None
            if fin is not None:
                fin.detach()     # normal release: the safety net must not
        _gc_defer_release()      # double-decrement the process refcount

    # ------------------------------------------------------------------ start/wait
    def start(self) -> None:
        """parsec_context_start (ref: scheduling.c:968): spawn workers, wake comm."""
        if self._started:
            return
        self._started = True
        if self.comm is not None:
            self.comm.enable()
        for s in self.streams[1:]:
            t = threading.Thread(target=self._worker_main, args=(s,),
                                 name=f"parsec-tpu-worker-{s.th_id}", daemon=True)
            self._workers.append(t)
            t.start()

    def test(self) -> bool:
        """parsec_context_test: True when no active taskpool remains."""
        with self._cv:
            return self._active == 0

    def wait(self, timeout: Optional[float] = None) -> int:
        """parsec_context_wait (ref: scheduling.c:994): master joins the hot loop."""
        self.start()
        self._progress_loop(self.streams[0],
                            until=lambda: self._active == 0,
                            timeout=timeout)
        return 0

    def wait_taskpool(self, tp: Taskpool, timeout: Optional[float] = None) -> bool:
        """parsec_taskpool_wait (ref: scheduling.c:1028)."""
        self.start()
        self._progress_loop(self.streams[0],
                            until=lambda: tp.completed,
                            timeout=timeout)
        return tp.completed

    def fini(self, timeout: Optional[float] = None) -> None:
        """parsec_fini: drain and join workers; report statistics
        (the per-thread usage + device statistics reports the reference
        prints at shutdown, scheduling.c:47-90 / device.c). After a body
        error the context is poisoned: fini skips the drain and tears down
        cleanly instead of re-raising. With ``timeout``, a drain that cannot
        finish (e.g. a peer rank died mid-graph) degrades to a warned
        teardown instead of hanging forever."""
        if self._finalized:
            return
        if self._error is None:
            try:
                self.wait(timeout=timeout)
            except TimeoutError:
                output.warning("fini: drain timed out with work outstanding; "
                               "tearing down anyway")
        self._finalized = True
        if self._ntrace is not None:
            # fini: land straggler ring events (blocking final drain)
            self._ntrace.drain_all(wait=True)
        if self.comm is not None and self.profiling is not None and \
                hasattr(self.comm, "stamp_clock_meta"):
            # the per-rank clock-offset metadata must land BEFORE any
            # dump: the multi-rank trace merge reads it to rebase this
            # rank's timestamps onto rank 0's clock. Finalize the ladder
            # first (bounded, collective — rank 0 answers the peers'
            # remaining pings here; only traced runs pay this), THEN
            # stamp, so the pump's result is what actually gets dumped
            try:
                if hasattr(self.comm, "clock_sync_finalize"):
                    self.comm.clock_sync_finalize(timeout=2.0)
                self.comm.stamp_clock_meta()
            except Exception:  # noqa: BLE001 — merge degrades to raw clocks
                pass
        if self._prof_auto and self.profiling is not None:
            try:
                self.profiling.dump()
            except OSError as e:
                output.warning(f"fini: trace dump failed: {e}")
        for s in self.streams:
            if s.nb_executed:
                output.debug_verbose(1, "stats",
                                     f"es{s.th_id} (vp{s.vp_id}): "
                                     f"{s.nb_executed} tasks, "
                                     f"{s.nb_selects} selects")
        for name, st in self.devices.statistics().items():
            if st["executed_tasks"]:
                output.debug_verbose(1, "stats", f"device {name}: {st}")
        self._work_event.set()
        for t in self._workers:
            t.join(timeout=5.0)
        if self._ptdev:
            # device lane down BEFORE the device modules: its manager
            # thread dispatches through them under the GIL
            self._ptdev.fini()
            self._ptdev = False
        self.devices.fini()
        if self.comm is not None:
            self.comm.fini()
        if self._dtd_neng is not None:
            # the per-context DTD engine never hits a per-pool detach
            # point: fold its buckets here so the process-wide registry
            # does not pin one engine per finished context forever
            self._hist_detach(self._dtd_neng)
        if self.sched_plane is not None:
            # same lifecycle for the plane's queue-wait histogram
            self._hist_detach(self.sched_plane.plane)
        # persist the online cost model (ISSUE 18) alongside the warm-
        # executable cache's lifecycle: a restarted serving process loads
        # it back at its first placement decision and starts warm
        from .costmodel import model as _cost_model
        _cost_model.maybe_save()
        if self.watchdog is not None:
            # watchdog before the endpoint: a dying context must not be
            # reported as a stall, and /health must answer to the end
            self.watchdog.stop()
            self.watchdog = None
        if self.metrics is not None:
            # endpoint down LAST: ops dashboards may scrape through the
            # drain, and the fini counter aggregation itself is scrapeable
            self.metrics.stop()
            self.metrics = None
        self._release_gc_hold()  # error paths can finalize w/ pools active

    # ------------------------------------------------------------------ scheduling
    def schedule(self, tasks, stream: Optional[ExecutionStream] = None,
                 distance: int = 0) -> None:
        """__parsec_schedule (ref: scheduling.c:287)."""
        if isinstance(tasks, Task):
            tasks = [tasks]
        tasks = list(tasks)
        if not tasks:
            return
        if self.paranoid:
            # PARANOID tier 1+ (ref: PARSEC_DEBUG_PARANOID build flavor):
            # a task entering the ready queues must actually be ready, and
            # must not already be completed/queued
            for t in tasks:
                # DTD tasks carry an explicit deps_remaining counter; PTG
                # readiness lives in the repo goal tables (base Task has no
                # such field)
                unmet = getattr(t, "deps_remaining", 0)
                if unmet > 0:
                    output.fatal(f"PARANOID: {t!r} scheduled with "
                                 f"{unmet} unmet dependencies")
                if t.status == TASK_STATUS_COMPLETE:
                    output.fatal(f"PARANOID: completed task {t!r} "
                                 f"re-scheduled")
        if not self._prio_seen:
            # burst selection is only policy-sound while every live task
            # has equal priority: the first prioritized task flips the hot
            # loop to task-at-a-time selects so releases preempt promptly
            for t in tasks:
                if t.priority:
                    self._prio_seen = True
                    break
        stream = stream or self._current_stream()
        if self.pins.enabled:
            self.pins.fire(pins_mod.SCHEDULE_BEGIN, stream, tasks)
            self.sched.schedule(stream, tasks, distance)
            self.pins.fire(pins_mod.SCHEDULE_END, stream, tasks)
        else:
            self.sched.schedule(stream, tasks, distance)
        if self._need_wake:
            self._work_event.set()

    def _current_stream(self) -> ExecutionStream:
        # threadlocal binding (workers bind in _worker_main); unknown
        # threads (user code, comm thread) act as the master stream
        return getattr(self._tls, "stream", None) or self.streams[0]

    # ------------------------------------------------------------ device lane
    def _ptdev_lane(self):
        """The context's native device lane (device/native.py), created
        lazily on the first TPU-bodied lane pool, or None when it cannot
        engage (no accelerator device, --mca device_native 0, module
        missing). The verdict is memoized — probing it per pool would
        retry a failed module load on every instantiation."""
        if self._ptdev is not None:
            return self._ptdev or None
        from ..device.native import NativeDeviceLane
        lane = NativeDeviceLane.maybe_create(self)
        self._ptdev = lane if lane is not None else False
        return lane

    # ------------------------------------------------------------ native lane
    def _ptexec_enqueue(self, tp: Taskpool, lane: Dict[str, Any]) -> None:
        """A PTG taskpool handed its whole FSM to the native execution
        lane (dsl/ptg/compiler.py _ptexec_prepare); every stream's hot
        loop drains it."""
        # ring lifecycle (enable): arm in-lane tracing before the first
        # burst so no lane event predates its rings
        self._ntrace_attach("ptexec", lane["graph"], tp.taskpool_id)
        self._hist_attach("ptexec", lane["graph"])
        if lane.get("dev_pool") is not None:
            # the device lane outlives pools; re-attach per enqueue
            # (idempotent) so a tracer attached AFTER the lane's creation
            # still lands this pool's EV_DEV_* events
            self._ntrace_attach("ptdev", lane["dev"].clane)
        with self._ptexec_lock:
            self._ptexec_q.append((tp, lane))
            if lane.get("pool_id") is not None:
                self._ptexec_comm_live += 1
            if lane.get("dev_pool") is not None:
                self._ptexec_dev_live += 1
            # scheduler plane, LAZY arming (the one-pool fast path): a
            # lone lane graph keeps its private allocation-free ready
            # vector — zero plane crossings on the 10M/s chain walk. The
            # moment a SECOND pool runs concurrently (or a pool carries
            # explicit QoS config), every queued lane binds: ready
            # structures migrate into the plane mid-run (safe hand-off,
            # see ptexec.cpp sched_bind) and the drain arbitrates by DRR
            if self.sched_plane is not None and (
                    len(self._ptexec_q) > 1
                    or getattr(tp, "qos_weight", None)
                    or getattr(tp, "admission_window", None)
                    or mca.get("sched_admission_window", 0)):
                for tp_i, lane_i in self._ptexec_q:
                    self._sched_pool_bind(tp_i, lane_i)
        self._work_event.set()

    def _sched_pool_bind(self, tp: Taskpool, lane: Dict[str, Any]) -> None:
        """Register ``tp`` on the scheduler plane and move its lane
        graph's ready structure there (idempotent; declines — full pool
        table, bind refusal — keep the private vector: engagement is
        unchanged, only cross-pool arbitration is lost)."""
        plane = self.sched_plane
        if plane is None or lane.get("sched_pool") is not None \
                or lane.get("finalized"):
            return
        h = plane.register_pool(tp.name, plane.KIND_PTEXEC,
                                weight=getattr(tp, "qos_weight", None),
                                window=getattr(tp, "admission_window",
                                               None))
        if h < 0:
            return
        try:
            lane["graph"].sched_bind(plane.capsule, h)
        except Exception:  # noqa: BLE001 — keep the private structure
            plane.unregister_pool(h)
            return
        lane["sched_pool"] = h

    def _ptexec_drain(self, stream: ExecutionStream) -> bool:
        """One burst through the front lane graph. The burst budget shrinks
        when this stream's scheduler queues hold work so a live lane cannot
        starve concurrently-active taskpools; the graph's run() never
        blocks, so a starved call returns straight to the hot loop.

        For data-flow pools the callback IS the data path: each batched
        dispatch reads its inputs from the lane's slot array, runs the
        bodies, lands outputs back into slots, and clears the slot ids the
        engine retired (the datarepo usagelmt/usagecnt protocol, kept in C)
        — generic_prepare_input / generic_release_deps never run for lane
        tasks. One callback per ~256 ready tasks amortizes the
        lane-crossing cost the per-task FSM used to pay on every task.

        With the scheduler plane armed and SEVERAL lane graphs queued,
        the pool to serve is picked by the plane's weighted DRR
        (next_ptexec) instead of always the FRONT graph — N concurrent
        taskpools then share the workers by QoS weight with a structural
        starvation bound, and the burst budget is capped by the pool's
        DRR quantum so one heavy pool cannot monopolize a worker between
        arbitration points (charge() spends the credits back)."""
        plane = self.sched_plane
        quantum = None
        pool_h = None
        with self._ptexec_lock:
            if not self._ptexec_q:
                return False
            tp, lane = self._ptexec_q[0]
            if plane is not None and len(self._ptexec_q) > 1:
                pick = plane.next_ptexec()
                if pick is not None:
                    h, quantum = pick
                    for tp_i, lane_i in self._ptexec_q:
                        if lane_i.get("sched_pool") == h:
                            tp, lane = tp_i, lane_i
                            pool_h = h
                            break
                    else:
                        quantum = None   # pool already retired: front graph
        graph = lane["graph"]
        # short bursts whenever (a) ordinary queues hold work, or (b) the
        # lane dispatches Python bodies (eager CTL callbacks or the
        # data-flow slot dispatcher) — a body-callback burst is bounded in
        # TASK count, not time, so a long budget would blind this stream
        # to newly scheduled tasks and peer errors for the whole burst.
        # Empty-body walks run >10M tasks/s, so the long budget still
        # returns within ~0.5s
        if lane["callback"] is not None or self.sched.has_local_work(stream):
            budget = 4096
        else:
            budget = 1 << 22
        if quantum is not None:
            # multi-pool arbitration: the burst spends this pool's DRR
            # credits, then returns to the arbiter for the next pick
            budget = max(256, min(budget, quantum))
        try:
            dv = lane.get("dev")
            if dv is not None:
                msg = dv.failed()
                if msg is not None:
                    # a device dispatch/poll callback raised on the lane's
                    # manager thread (which has no caller to propagate
                    # to): surface it here as the pool's error
                    raise RuntimeError(
                        f"native device lane callback failed: {msg}")
            mine = graph.run(lane["callback"], 256, budget, stream.th_id)
            if mine == 0 and (lane.get("pool_id") is not None
                              or lane.get("dev_pool") is not None) \
                    and not graph.failed() and not graph.done():
                # comm- or device-bound lane starved mid-graph: the next
                # ready task arrives from the comm progress thread or the
                # device manager thread (both GIL-free/GIL-taking off
                # this loop), not from this process's walk — micro-poll
                # briefly instead of paying a full hot-loop iteration per
                # hop (bounded: ~1ms, then the outer loop resumes its
                # usual error/deadline/device servicing)
                for spin in range(224):
                    # yield-spin first (the GIL is free: the comm thread
                    # runs without it), then ease into short naps
                    time.sleep(0 if spin < 200 else 2e-5)
                    mine = graph.run(lane["callback"], 256, budget,
                                     stream.th_id)
                    if mine or graph.failed() or graph.done():
                        break
        except BaseException as e:  # noqa: BLE001 — a body raised
            with self._ptexec_lock:
                self._ptexec_retire_locked(lane)
            self._ptexec_abandon(lane)
            if self._error is None:
                self._error = e
            self._work_event.set()
            if stream.is_master:
                raise           # workers park; the master surfaces the error
            return True
        stream.nb_executed += mine
        if pool_h is not None and mine:
            plane.charge(pool_h, mine)
        if graph.failed():
            # poisoned by another stream's body exception: that stream
            # owns the propagation; just retire the queue entry
            with self._ptexec_lock:
                self._ptexec_retire_locked(lane)
            self._ptexec_abandon(lane)
            return True
        if graph.done():
            fin = False
            with self._ptexec_lock:
                if not lane.get("finalized"):
                    lane["finalized"] = True
                    fin = True
                self._ptexec_retire_locked(lane)
            if fin:
                tp._ptexec_finalize(lane)
                # ring lifecycle (quiescence): land the finished graph's
                # events and stop pinning it
                self._ntrace_detach(lane["graph"])
                self._hist_detach(lane["graph"])
                self._cost_fold(lane)
                self._sched_pool_retire(lane)
            return True
        return mine > 0

    def _ptexec_retire_locked(self, lane: Dict[str, Any]) -> None:
        """Drop ``lane`` from the drain queue wherever it sits (the DRR
        arbiter serves graphs out of front order). _ptexec_lock held."""
        for i, (_tp, l_) in enumerate(self._ptexec_q):
            if l_ is lane:
                self._ptexec_q.pop(i)
                if lane.get("pool_id") is not None:
                    self._ptexec_comm_live -= 1
                if lane.get("dev_pool") is not None:
                    self._ptexec_dev_live -= 1
                return

    def _sched_pool_retire(self, lane: Dict[str, Any]) -> None:
        """Free a finished/errored lane graph's scheduler-plane pool slot
        (idempotent: sched_unbind on an unbound graph is a no-op)."""
        h = lane.get("sched_pool")
        if h is None or self.sched_plane is None:
            return
        try:
            # the GRAPH owns its slot (sched_unbind frees it natively);
            # the wrapper only forgets the name mapping — a second free
            # here could kill an unrelated pool that reused the slot
            lane["graph"].sched_unbind()
        except Exception:  # noqa: BLE001 — a peer is still mid-batch
            return      # (poisoned graph): keep the handle so the next
                        # stream's abandon retries; dealloc frees anyway
        lane.pop("sched_pool", None)
        self.sched_plane.forget_pool(h)

    def _dtd_drain(self, stream: ExecutionStream) -> bool:
        """One burst through the DTD engine's batched ready-drain (the
        in-lane execute of the batched insert lane, ISSUE 4): pops ready
        batch-lane tasks, runs their bodies through per-class batched
        callbacks, and feeds completions straight back into the release
        walk without surfacing intermediate ids. Only newly-ready
        PER-TASK-lane successors come back (`surfaced`) and enter the
        ordinary scheduler. Body exceptions poison the engine lane and
        propagate through the usual error machinery."""
        eng = self._dtd_neng
        if eng is None:
            return False
        try:
            nexec, surfaced = eng.drain_ready(256, 4096, stream.th_id)
        except BaseException as e:  # noqa: BLE001 — a batched body raised
            if self._error is None:
                self._error = e
            self._work_event.set()
            if stream.is_master:
                raise
            return True
        if nexec:
            stream.nb_executed += nexec
        if surfaced:
            ntasks = self._dtd_ntasks
            rtasks = []
            for rid in surfaced:
                t = ntasks[rid]
                t.deps_remaining = 0    # paranoid-check coherence
                rtasks.append(t)
            self.schedule(rtasks, stream)
        return nexec > 0 or bool(surfaced)

    def _ptexec_abandon(self, lane: Dict[str, Any]) -> None:
        """Drop an errored data-mode lane's slot payloads. Each stream
        that exits the poisoned graph attempts this; the LAST one out
        (graph idle — after a poison no worker can claim a new batch, so
        idleness is stable) clears the payload list. Clearing earlier
        would yank inputs out from under a peer still mid-callback;
        leaking instead would pin every produced payload for the
        taskpool's remaining lifetime."""
        self._ntrace_detach(lane["graph"])   # final drain of an errored lane
        self._hist_detach(lane["graph"])
        self._cost_fold(lane)                # idempotent (pop-guarded)
        self._sched_pool_retire(lane)        # free the plane pool slot
        if lane.get("dev_pool") is not None:
            # stop routing the poisoned pool's device completions (in-
            # flight retires for it count late_retires, never land)
            lane["dev"].unbind_pool(lane.pop("dev_pool"))
        slots = lane.get("slots")
        if not slots:
            return
        with self._ptexec_lock:
            if lane.get("finalized") or not lane["graph"].idle():
                return
            lane["finalized"] = True
        slots.clear()


    # ------------------------------------------------------------------ hot loop
    def _worker_main(self, stream: ExecutionStream) -> None:
        self._tls.stream = stream
        if mca.get("runtime_bind_threads", False):
            from .vpmap import bind_current_thread
            bind_current_thread(self.vpmap.core_of(stream.th_id))
        while not self._finalized:
            self._progress_loop(stream, until=lambda: self._active == 0)
            # park until new work shows up
            self._work_event.wait(timeout=0.05)
            self._work_event.clear()

    def in_progress_loop(self) -> bool:
        """True when the CALLING thread is inside a progress loop — i.e. a
        task body may be on its call stack. Flow-control blocking (the DTD
        window stall) consults this: blocking mid-body can deadlock the
        pool (the unfinished task's successors may be the only drainable
        work). THREAD-local on purpose — all user threads share the master
        stream object, so stream-level state would let one thread's
        wait() mask another thread's top-level inserts (and the unlocked
        += on a shared counter could corrupt it permanently)."""
        return getattr(self._tls, "loop_depth", 0) > 0

    def _progress_loop(self, stream: ExecutionStream, until, timeout=None) -> None:
        """The hot loop (ref: __parsec_context_wait scheduling.c:789-818).

        Master-stream loops are serialized (one driving thread at a time,
        see ``_master_loop_lock``). A contender must NOT block on the
        lock unconditionally — the holder's exit condition may require
        the contender to make progress elsewhere (e.g. wait() holds while
        a window-stalled inserter contends: the pool cannot complete
        until the inserter resumes) — so contenders poll their OWN
        ``until`` (and the error flag, and their deadline) between short
        acquire attempts; the holder is draining the same work anyway."""
        tls = self._tls
        depth = getattr(tls, "loop_depth", 0)
        tls.loop_depth = depth + 1
        try:
            if stream.th_id != 0:
                self._progress_loop_inner(stream, until, timeout)
                return
            deadline = None if timeout is None \
                else time.monotonic() + timeout
            while True:
                if until():
                    return
                if self._error is not None:
                    raise self._error
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return
                    slice_ = min(0.02, left)
                else:
                    slice_ = 0.02
                if self._master_loop_lock.acquire(timeout=slice_):
                    try:
                        self._progress_loop_inner(
                            stream, until,
                            None if deadline is None
                            else max(0.0, deadline - time.monotonic()))
                    finally:
                        self._master_loop_lock.release()
                    return
        finally:
            tls.loop_depth = depth

    def _progress_loop_inner(self, stream: ExecutionStream, until,
                             timeout=None) -> None:
        misses = 0
        deadline = None if timeout is None else time.monotonic() + timeout
        backoff_max = mca.get("runtime_backoff_max_us", 1000) / 1e6
        self._run_drain_hooks()
        while not until():
            if self._error is not None:
                if stream.is_master:
                    raise self._error
                return  # workers park quietly; the master surfaces the error
            did_something = False
            # master progresses communications inline (ref: scheduling.c:790-798)
            if stream.is_master and self.comm is not None:
                did_something |= bool(self.comm.progress())
            # poll device modules (our analogue of the GPU manager thread)
            did_something |= bool(self.devices.progress(stream))
            # native PTG execution lane: join the front graph's batched C
            # walk (returns promptly when starved — see _ptexec_drain)
            if self._ptexec_q:
                did_something |= self._ptexec_drain(stream)
            task = stream.next_task
            stream.next_task = None
            distance = 0
            if task is None:
                if self.pins.enabled:
                    self.pins.fire(pins_mod.SELECT_BEGIN, stream, None)
                    task, distance = self.sched.select(stream)
                    self.pins.fire(pins_mod.SELECT_END, stream, task)
                else:
                    task, distance = self.sched.select(stream)
                stream.nb_selects += 1
            if task is None and self._dtd_batch_pools:
                # native DTD batched lane: drain the engine's internal
                # ready structure through per-class batched callbacks.
                # AFTER the scheduler select on purpose: batched tasks all
                # carry priority 0 (prioritized inserts ride the per-task
                # lane), so scheduler-queued work — which includes every
                # prioritized task — must preempt the batch backfill, the
                # same policy order the interpreted FSM's priority-sorted
                # queues give
                did_something |= self._dtd_drain(stream)
            if task is not None:
                misses = 0
                # drain a burst before re-checking the loop conditions: the
                # per-iteration overhead (until, error, comm, device polls)
                # is pure cost for fine-grain tasks, and the scheduler pops
                # the whole burst under ONE lock (select_burst). Bursts
                # skip the SELECT pins events, so instrumentation keeps the
                # task-at-a-time shape
                budget = 1 if self.pins.enabled else 32
                use_burst = not (self.pins.enabled or self._prio_seen)
                batch: List[Task] = []
                bi = 0
                try:
                    while True:
                        self._task_progress(stream, task, distance)
                        budget -= 1
                        task = stream.next_task
                        if task is not None:
                            if budget <= 0:
                                # outer loop consumes next_task; un-run
                                # burst tasks go back to the queues
                                if bi < len(batch):
                                    self.sched.schedule(stream, batch[bi:], 0)
                                break
                            stream.next_task = None
                            distance = 0
                            continue
                        if bi < len(batch):
                            task = batch[bi]
                            bi += 1
                            distance = 0
                            continue
                        if budget <= 0:
                            break
                        if use_burst:
                            batch = self.sched.select_burst(stream, budget)
                            stream.nb_selects += 1
                            bi = 0
                            if not batch:
                                break
                            task = batch[0]
                            bi = 1
                        else:
                            # prioritized workload: task-at-a-time selects
                            # keep just-released high-priority work first
                            task, distance = self.sched.select(stream)
                            stream.nb_selects += 1
                            if task is None:
                                break
                            continue
                        distance = 0
                except BaseException as e:  # noqa: BLE001
                    # a failing body must surface to every waiter, not die
                    # silently with one worker thread (ref: hook errors are
                    # fatal, scheduling.c:541-548)
                    if self._error is None:
                        self._error = e
                    if bi < len(batch):     # un-run burst tasks stay queued
                        try:
                            self.sched.schedule(stream, batch[bi:], 0)
                        except Exception:
                            pass
                    self._work_event.set()
                    if stream.is_master:
                        raise
                    return
                did_something = True
            if not did_something:
                misses += 1
                self._run_drain_hooks()   # starving: drain buffers
                if deadline is not None and time.monotonic() > deadline:
                    return
                # exponential backoff while starving (ref: scheduling.c:801-804)
                # — capped near the wire latency while a comm-bound lane
                # graph is in flight: its next ready task arrives from
                # the comm progress thread, not from this process, and a
                # ms-scale sleep would dominate every cross-rank hop
                cap = 2e-5 if (self._ptexec_comm_live
                               or self._ptexec_dev_live) else backoff_max
                if cap == backoff_max and self.sched_plane is not None \
                        and (self._ptexec_q or self._dtd_batch_pools) \
                        and self.sched_plane.queued_total() > 0:
                    # "no local work" is NOT global with multiple pools:
                    # this stream's last pick starved, but the plane holds
                    # queued work (another pool's overflow spill) a fresh
                    # arbitration round will hand out — stay hot instead
                    # of parking a worker against a non-empty plane
                    cap = 2e-5
                time.sleep(min(cap, 1e-6 * (1 << min(misses, 10))))

    # ------------------------------------------------------------------ task FSM
    def _task_progress(self, stream: ExecutionStream, task: Task,
                       distance: int = 0) -> int:
        """__parsec_task_progress (ref: scheduling.c:507)."""
        tc = task.task_class
        if getattr(task, "nid", -1) >= 0 and not self.pins.paranoid \
                and not self.paranoid and tc.fast_inline and not tc.jit_ok:
            # DTD native fast lane: eager CPU body, synchronous completion
            # — one fused call replaces the prepare/execute/complete FSM.
            # Profiling no longer ejects tasks from this lane (the PR 5
            # observer-effect removal): with PINS enabled the lean cycle
            # fires the core lifecycle events itself, and only --mca
            # pins_paranoid 1 restores the full per-task FSM
            task.taskpool._lean_cycle(stream, task)
            return HOOK_DONE
        if task.status < TASK_STATUS_PREPARE_INPUT:
            task.status = TASK_STATUS_PREPARE_INPUT
            pins_on = self.pins.enabled
            if tc.prepare_input is None and not tc.flows and not pins_on:
                # nothing to resolve — but only skip the PREPARE pins
                # events when instrumentation is off (trace consumers pair
                # intervals and must see symmetric streams per task)
                return self._execute(stream, task)
            if pins_on:
                self.pins.fire(pins_mod.PREPARE_INPUT_BEGIN, stream, task)
            if tc.prepare_input is not None:
                rc = tc.prepare_input(stream, task)
            else:
                rc = self.generic_prepare_input(stream, task)
            if pins_on:
                self.pins.fire(pins_mod.PREPARE_INPUT_END, stream, task)
            if rc == HOOK_AGAIN:
                self.schedule([task], stream, distance)
                return rc
        return self._execute(stream, task)

    def _execute(self, stream: ExecutionStream, task: Task) -> int:
        """__parsec_execute (ref: scheduling.c:126)."""
        tc = task.task_class
        task.status = TASK_STATUS_HOOK
        device = self.devices.select_best_device(task)  # ref: device.c:100
        task.selected_device = device
        for chore in tc.incarnations:
            if not (chore.device_type & task.chore_mask):
                continue
            if device is not None and not (chore.device_type & device.type):
                continue
            if chore.evaluate is not None:
                ev = chore.evaluate(stream, task)
                if ev == HOOK_NEXT:
                    continue
                if ev == HOOK_DISABLE:
                    task.chore_mask &= ~chore.device_type
                    continue
            task.selected_chore = chore
            pins_on = self.pins.enabled
            if pins_on:
                self.pins.fire(pins_mod.EXEC_BEGIN, stream, task)
            rc = chore.hook(stream, task)
            stream.nb_executed += 1
            # return-code dispatch (ref: scheduling.c:518-566)
            if rc == HOOK_DONE:
                if pins_on:
                    self.pins.fire(pins_mod.EXEC_END, stream, task)
                if device is not None:
                    device.executed_tasks += 1  # async devices count in epilog
                self.complete_task_execution(stream, task)
                return rc
            if rc == HOOK_ASYNC:
                # completion arrives via complete_task_execution from a
                # device; the EXEC interval closes here (it measures host
                # dispatch — device execution shows on the device's own
                # profiling stream)
                if pins_on:
                    self.pins.fire(pins_mod.EXEC_END, stream, task)
                return rc
            if rc == HOOK_AGAIN:
                if pins_on:
                    self.pins.fire(pins_mod.EXEC_END, stream, task)
                self.schedule([task], stream, distance=1)  # __parsec_reschedule :445
                return rc
            if rc == HOOK_NEXT:
                continue
            if rc == HOOK_DISABLE:
                task.chore_mask &= ~chore.device_type
                continue
            if rc == HOOK_ERROR:
                output.fatal(f"task {task!r} hook failed")  # ref: scheduling.c:541-548
        output.fatal(f"no runnable chore for task {task!r} "
                     f"(chore_mask={task.chore_mask:#x})")
        return HOOK_ERROR

    def complete_task_execution(self, stream: ExecutionStream, task: Task) -> None:
        """__parsec_complete_execution (ref: scheduling.c:469)."""
        tc = task.task_class
        if self.paranoid and task.status == TASK_STATUS_COMPLETE:
            output.fatal(f"PARANOID: {task!r} completed twice")
        task.status = TASK_STATUS_COMPLETE
        pins_on = self.pins.enabled
        if pins_on:
            self.pins.fire(pins_mod.COMPLETE_EXEC_BEGIN, stream, task)
        if tc.prepare_output is not None:
            tc.prepare_output(stream, task)
        if tc.complete_execution is not None:
            tc.complete_execution(stream, task)
        if pins_on:
            self.pins.fire(pins_mod.RELEASE_DEPS_BEGIN, stream, task)
        if tc.release_deps is not None:
            tc.release_deps(stream, task)
        else:
            self.generic_release_deps(stream, task)
        if pins_on:
            self.pins.fire(pins_mod.RELEASE_DEPS_END, stream, task)
            self.pins.fire(pins_mod.COMPLETE_EXEC_END, stream, task)
        if task.on_complete is not None:
            task.on_complete(task)
        task.taskpool.addto_nb_tasks(-1)
        if tc.release_task is not None:
            tc.release_task(stream, task)

    # ------------------------------------------------------------------ deps engine
    def generic_prepare_input(self, stream: ExecutionStream, task: Task) -> int:
        """Generic data_lookup: resolve input copies from repos / collections
        (the role of the generated data_lookup, ref: jdf2c.c:45)."""
        tp = task.taskpool
        for flow in task.task_class.flows:
            slot = task.data[flow.flow_index]
            if slot.data_in is not None or flow.access & FLOW_ACCESS_CTL:
                continue
            for dep in flow.deps_in:
                if dep.cond is not None and not dep.cond(task.locals):
                    continue
                if dep.task_class is None:
                    # direct read from a data collection (JDF: "A <- A(k)")
                    if dep.data_ref is not None:
                        data = dep.data_ref(task.locals)
                        slot.data_in = data.get_copy() if hasattr(data, "get_copy") else data
                else:
                    plocals_seq = dep.target_locals(task.locals) if dep.target_locals else [task.locals]
                    plocals = plocals_seq[0] if not isinstance(plocals_seq, dict) else plocals_seq
                    pkey = dep.task_class.make_key(tp, plocals)
                    repo = tp.repos[dep.task_class.task_class_id]
                    entry = repo.lookup_entry(pkey) if repo is not None else None
                    if entry is None:
                        output.fatal(f"missing repo entry {pkey} for {task!r} flow {flow.name}")
                    slot.data_in = entry.data[dep.flow_index]
                    slot.source_repo_entry = entry
                break
        return HOOK_DONE

    def generic_release_deps(self, stream: ExecutionStream, task: Task) -> None:
        """Generic release-deps (ref: parsec_release_dep_fct parsec.c:1837).

        Walks output deps, updates successor dependency masks/counters
        (parsec.c:1657), collects newly-ready tasks into a ring and schedules
        it (scheduling keeps the highest-priority task as ``next_task``,
        ref: __parsec_schedule_vp scheduling.c:360).
        """
        tp = task.taskpool
        tc = task.task_class
        ready: List[Task] = []
        # publish produced copies into this class's repo for local successors
        repo = tp.repos[tc.task_class_id]
        # publish every flow that local successors will consume — written
        # flows and forwarded reads alike (count_deps_fct role, parsec.c:1448)
        wants_repo = repo is not None and any(
            any(d.task_class is not None for d in f.deps_out)
            for f in tc.flows if not (f.access & FLOW_ACCESS_CTL))
        entry = None
        nb_uses = 0
        if wants_repo:
            entry = repo.lookup_entry_and_create(task.key)
            for f in tc.flows:
                if f.deps_out and not (f.access & FLOW_ACCESS_CTL):
                    slot = task.data[f.flow_index]
                    out = slot.data_out if slot.data_out is not None else slot.data_in
                    entry.data[f.flow_index] = out

        distributed = self.comm is not None and self.nb_ranks > 1

        def visit(dep, succ_locals: Dict[str, int]) -> bool:
            succ_tc = dep.task_class
            key = succ_tc.make_key(tp, succ_locals)
            contribution = 1 if succ_tc.count_mode else (1 << dep.dep_index)
            goal = (succ_tc.dependencies_goal_fn(succ_locals)
                    if succ_tc.dependencies_goal_fn is not None else None)
            if tp.update_deps(succ_tc, key, contribution, goal):
                t = self.make_task(tp, succ_tc, dict(succ_locals))
                ready.append(t)
            return True

        for flow in tc.flows:
            # remote destinations grouped by the out-dep's named datatype:
            # each type is reshaped ONCE before the wire and packed once per
            # destination set (pre-send remote reshape, parsec/remote_dep.h:117;
            # remote_multiple_outs_same_pred_flow.jdf)
            remote_by_dtt: Dict[Optional[str], set] = {}
            null_checked = False
            for dep in flow.deps_out:
                if dep.cond is not None and not dep.cond(task.locals):
                    continue
                if dep.task_class is None:
                    continue  # write-back to memory handled by the body/copy model
                if not null_checked and not (flow.access & FLOW_ACCESS_CTL):
                    # forwarding no-data on a data flow is a program bug the
                    # runtime must catch at the source (ref: "A NULL is
                    # forwarded", parsec.c:1879; ptgpp forward_*_NULL tests)
                    null_checked = True
                    slot = task.data[flow.flow_index]
                    out = slot.data_out if slot.data_out is not None \
                        else slot.data_in
                    if (out.payload if hasattr(out, "payload") else out) is None:
                        output.fatal(
                            f"A NULL is forwarded\n"
                            f"\tfrom: {tc.name}{task.key} flow {flow.name}\n"
                            f"\tto:   {dep.task_class.name}")
                targets = dep.target_locals(task.locals) if dep.target_locals else [task.locals]
                if isinstance(targets, dict):
                    targets = [targets]
                for tl in targets:
                    if distributed:
                        r = tp.task_rank_of(dep.task_class, tl)
                        if r != self.my_rank:
                            # remote successor: ship this flow's output once
                            # per destination (the remote activation fork of
                            # parsec_release_dep_fct); [type_remote]
                            # overrides [type] on the wire
                            wire = getattr(dep, "wire_datatype", dep.datatype)
                            remote_by_dtt.setdefault(wire, set()).add(r)
                            continue
                    visit(dep, tl)
                    if not (flow.access & FLOW_ACCESS_CTL):
                        # CTL consumers never look the entry up (their
                        # prepare_input skips data resolution), so counting
                        # them in the usage limit would make the entry
                        # unretirable
                        nb_uses += 1
            if remote_by_dtt:
                slot = task.data[flow.flow_index]
                out = slot.data_out if slot.data_out is not None else slot.data_in
                payload = out.payload if hasattr(out, "payload") else out
                dtt_of = getattr(tp, "_dtt", None)
                ck = getattr(tc, "_ptg_canonical_key", None)
                wire_key = ck(task) if ck is not None else task.key
                for dtt_name, ranks in remote_by_dtt.items():
                    wire_payload = payload
                    if dtt_name is not None and dtt_of is not None:
                        dtt = dtt_of(dtt_name)
                        if dtt is not None and not dtt.identity:
                            wire_payload = dtt.extract(payload)
                    self.comm.ptg_send(tp, tc, wire_key, flow.flow_index,
                                       wire_payload, sorted(ranks),
                                       dtt=dtt_name)
        if entry is not None:
            repo.entry_addto_usage_limit(task.key, max(nb_uses, 1))
        # consume source repo entries (one use each)
        for flow in tc.flows:
            slot = task.data[flow.flow_index]
            if slot.source_repo_entry is not None:
                slot.source_repo_entry._repo.entry_used_once(slot.source_repo_entry.key)
        if ready:
            ready.sort(key=lambda t: -t.priority)
            # only claim the hot-path slot when it is free: device epilogs can
            # release several tasks on the same stream within one progress
            # sweep, and overwriting a pending next_task would lose it forever
            # (mirrors __parsec_schedule_vp pushing the displaced task back)
            if stream.next_task is None:
                stream.next_task, rest = ready[0], ready[1:]
            else:
                rest = ready
            if rest:
                self.schedule(rest, stream)

    def make_task(self, tp: Taskpool, tc: TaskClass,
                  locals_: Dict[str, int], priority: Optional[int] = None) -> Task:
        if priority is None:
            prio = tc.properties.get("priority", 0)
            priority = prio(locals_) if callable(prio) else prio
        return Task(tp, tc, locals_, priority)


# ---------------------------------------------------------------------------
# module-level convenience mirroring parsec_init/parsec_fini
# ---------------------------------------------------------------------------
_default_context: Optional[Context] = None


def init(nb_cores: Optional[int] = None, argv: Optional[List[str]] = None,
         **kw) -> Context:
    """parsec_init equivalent (ref: parsec/parsec.c:405)."""
    global _default_context
    if _default_context is None or _default_context._finalized:
        _default_context = Context(nb_cores=nb_cores, argv=argv, **kw)
    return _default_context


def fini() -> None:
    global _default_context
    if _default_context is not None:
        _default_context.fini()
        _default_context = None
