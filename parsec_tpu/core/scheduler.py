"""Pluggable ready-queue scheduling modules.

Re-design of parsec/mca/sched (module interface: parsec/mca/sched/sched.h:210-335).
A scheduler module provides ``install / flow_init / schedule / select / remove``;
``schedule`` receives a *distance* hint conveying steal/locality distance exactly
as in the reference. The module is selected at runtime through the MCA parameter
``sched`` (ref: parsec_set_scheduler, parsec/scheduling.c:249-275).

Module set mirrors the reference's (parsec/mca/sched/*):

=========  =====================================================================
``lfq``    local flat queues + hierarchical bounded buffers + work stealing
           (default, priority 20; ref: sched_lfq_component.c:73)
``gd``     single global dequeue (sched_gd)
``ltq``    local tree queues (approximated: local heaps, subtree-biased steal)
``lhq``    local hierarchical queues
``ap``     absolute priority: one global priority heap (sched_ap)
``pbq``    priority-based local queues + steal (sched_pbq)
``ip``     inverse priority (sched_ip)
``ll``     local LIFO + steal (sched_ll)
``llp``    local LIFO with priorities (sched_llp)
``rnd``    random global queue (sched_rnd)
``spq``    shared priority queue (sched_spq)
=========  =====================================================================

On TPU the scheduler's job is mostly *dispatch ordering*: bodies are issued
asynchronously to the device stream, so queue policy governs pipeline depth and
data locality (which tiles stay HBM-resident), not CPU load balance.
"""

from __future__ import annotations

import bisect
import operator
import heapq
import itertools
import random
import sys
import threading
import weakref
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

from ..utils import mca, output
from .task import Task

mca.register("sched", "lfq", "Scheduler module (lfq|gd|ltq|lhq|ap|pbq|ip|ll|llp|rnd|spq)")


class SchedulerModule:
    """Module interface (ref: parsec/mca/sched/sched.h:210-335)."""

    name = "base"
    priority = 0  # component selection priority, highest wins

    #: native arbitration flavor of this policy on the scheduler plane
    #: (native/src/ptsched.h): "wdrr" | "fifo" | "prio" | "rndsteal", or
    #: None when the policy has no native analogue — the plane then
    #: declines (counted in SCHED_STATS["policy_fallback"]) and every
    #: engine keeps its private ready structure, so ``--mca sched <name>``
    #: selects ordering UNIFORMLY across interpreted and native paths
    #: (docs/scheduling.md has the full matrix)
    native_policy: Optional[str] = None

    def install(self, context) -> None:
        self.context = context
        self._register_py_counters()

    def stats_global(self) -> Dict[str, int]:
        """Module-WIDE queue depths (not per-stream): the ``sched.py.*``
        registry export, so interpreted and native runs publish the same
        shape of scheduler observability (``sched.queued`` vs
        ``sched.py.queued``) instead of consumers hand-poking per-stream
        stats() dicts."""
        return {}

    def _register_py_counters(self) -> None:
        """Route this module through the unified counter registry as
        ``sched.py.*`` (weakly bound: a finished context's module must
        not be pinned by the process-wide registry; the latest installed
        module wins the name, matching the one-live-context norm)."""
        from ..utils.counters import counters
        ref = weakref.ref(self)

        def _mk(key):
            def sample():
                m = ref()
                if m is None:
                    return 0
                try:
                    return m.stats_global().get(key, 0)
                except Exception:  # noqa: BLE001 — sampling never breaks
                    return 0
            return sample

        for key in ("queued", "local_len", "system_len"):
            counters.register(f"sched.py.{key}", sampler=_mk(key))

    def flow_init(self, stream) -> None:
        """Per-execution-stream initialization (ref: flow_init + barrier)."""

    def schedule(self, stream, tasks: Iterable[Task], distance: int = 0) -> None:
        raise NotImplementedError

    def select(self, stream) -> Tuple[Optional[Task], int]:
        """Return (task, distance-it-came-from) or (None, 0)."""
        raise NotImplementedError

    def select_burst(self, stream, n: int) -> List[Task]:
        """Pop up to ``n`` tasks in policy order. Default: loop select().
        Queue-backed modules override with a single-lock bulk pop — the
        per-call overhead an interpreted hot loop cannot amortize one task
        at a time."""
        out = []
        for _ in range(n):
            t, _d = self.select(stream)
            if t is None:
                break
            out.append(t)
        return out

    def stats(self, stream) -> Dict[str, int]:
        return {}

    def has_local_work(self, stream) -> bool:
        """Cheap peek: does this stream see queued tasks without popping?
        The native execution lane (core/context.py:_ptexec_drain) sizes
        its bursts by this — a live lane must interleave with, not starve,
        taskpools riding the ordinary queues. False negatives only cost
        one long burst; the default is safe for modules without queues."""
        return False

    def remove(self, context) -> None:
        pass


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

class _LockedDeque:
    """Thread-safe dequeue with NO explicit lock: every operation is a
    single collections.deque call, which CPython guarantees atomic under
    the GIL (append/extend/popleft/pop). Emptiness is handled by catching
    IndexError instead of check-then-act — the name is kept for its role
    (the reference's parsec_dequeue, which does lock). On free-threaded
    interpreters the module swaps in :class:`_ExplicitLockedDeque` below."""

    __slots__ = ("dq",)

    def __init__(self) -> None:
        self.dq: deque = deque()

    def push_front(self, items) -> None:
        self.dq.extendleft(reversed(items))

    def push_back(self, items) -> None:
        self.dq.extend(items)

    def pop_front(self):
        try:
            return self.dq.popleft()
        except IndexError:
            return None

    def pop_back(self):
        try:
            return self.dq.pop()
        except IndexError:
            return None

    def __len__(self) -> int:
        return len(self.dq)


class _ExplicitLockedDeque:
    """Lock-based deque with the same surface as :class:`_LockedDeque`, for
    free-threaded CPython (PEP 703, 3.13t+) where the GIL atomicity the
    no-lock variant relies on is gone."""

    __slots__ = ("dq", "lock")

    def __init__(self) -> None:
        self.dq: deque = deque()
        self.lock = threading.Lock()

    def push_front(self, items) -> None:
        with self.lock:
            self.dq.extendleft(reversed(items))

    def push_back(self, items) -> None:
        with self.lock:
            self.dq.extend(items)

    def pop_front(self):
        with self.lock:
            try:
                return self.dq.popleft()
            except IndexError:
                return None

    def pop_back(self):
        with self.lock:
            try:
                return self.dq.pop()
            except IndexError:
                return None

    def __len__(self) -> int:
        return len(self.dq)


# checked once at import — the interpreter cannot change GIL mode mid-process
if not getattr(sys, "_is_gil_enabled", lambda: True)():  # pragma: no cover
    _LockedDeque = _ExplicitLockedDeque  # noqa: F811


class _LockedHeap:
    """Priority heap; highest priority pops first (ties FIFO)."""

    __slots__ = ("heap", "lock", "_ctr")

    def __init__(self) -> None:
        self.heap: List = []
        self.lock = threading.Lock()
        self._ctr = itertools.count()

    def push(self, task: Task, sign: int = -1, tie_lifo: bool = False) -> None:
        with self.lock:
            # counter drawn under the lock: acquisition order == insertion
            # order, so the FIFO/LIFO tiebreak among equal priorities holds
            ctr = next(self._ctr)
            heapq.heappush(self.heap,
                           (sign * task.priority,
                            -ctr if tie_lifo else ctr, task))

    def pop(self) -> Optional[Task]:
        with self.lock:
            if not self.heap:
                return None
            return heapq.heappop(self.heap)[2]

    def __len__(self) -> int:
        return len(self.heap)


_PRIO_KEY = operator.attrgetter("priority")


class _HBBuffer:
    """Hierarchical bounded buffer (redesign of parsec/hbbuffer.c:1-278):
    fixed capacity; overflow spills through ``parent_push`` (another buffer
    or the system dequeue); ``pop_best`` removes the highest-priority
    element, ``pop_any`` the coldest (steal end).

    Ordering is LAZY: pushes only mark the buffer dirty and the sort runs
    at the next pop — bulk producers (the DTD ready batch) would otherwise
    pay a full re-sort per push. Timsort makes the all-equal-priority case
    (the common one) a single O(n) scan."""

    __slots__ = ("cap", "items", "lock", "parent_push", "_dirty")

    def __init__(self, cap: int, parent_push) -> None:
        self.cap = max(1, cap)
        self.items: List[Task] = []     # ascending priority; best at the end
        self.lock = threading.Lock()
        self.parent_push = parent_push
        self._dirty = False

    def _ensure_sorted(self) -> None:   # call with self.lock held
        if self._dirty:
            self.items.sort(key=_PRIO_KEY)
            self._dirty = False

    def push(self, tasks: List[Task]) -> None:
        """Fill to capacity, spill the rest upward (hbbuffer_push_all)."""
        with self.lock:
            room = self.cap - len(self.items)
            take, spill = tasks[:room], tasks[room:]
            if take:
                self.items.extend(take)
                self._dirty = True
        if spill:
            self.parent_push(spill)

    def push_by_priority(self, tasks: List[Task]) -> None:
        """Merge then spill the LOWEST-priority overflow upward
        (hbbuffer_push_all_by_priority): hot tasks stay local."""
        with self.lock:
            self.items.extend(tasks)
            self.items.sort(key=_PRIO_KEY)
            self._dirty = False
            nspill = len(self.items) - self.cap
            spill, self.items = (self.items[:nspill], self.items[nspill:]) \
                if nspill > 0 else ([], self.items)
        if spill:
            self.parent_push(spill)

    def pop_best(self) -> Optional[Task]:
        with self.lock:
            if not self.items:
                return None
            self._ensure_sorted()
            return self.items.pop()

    def pop_best_burst(self, n: int) -> List[Task]:
        """Up to ``n`` highest-priority items, one lock."""
        with self.lock:
            items = self.items
            k = min(n, len(items))
            if not k:
                return []
            self._ensure_sorted()
            batch = items[-k:]
            del items[-k:]
        batch.reverse()          # best first
        return batch

    def pop_any(self) -> Optional[Task]:
        with self.lock:
            if not self.items:
                return None
            self._ensure_sorted()
            return self.items.pop(0)

    def __len__(self) -> int:
        return len(self.items)


class _LocalQueuesBase(SchedulerModule):
    """Shared plumbing for the local-queues family: per-stream structures,
    a shared system dequeue, and the distance-ordered steal walk
    (ref: parsec/mca/sched/sched_local_queues_utils.h)."""

    def install(self, context) -> None:
        super().install(context)
        self._queues: Dict[int, object] = {}
        self._order: List[int] = []
        self._system = _LockedDeque()
        self._init_lock = threading.Lock()
        self._steal_cache: Dict[int, List[int]] = {}

    def _system_push(self, tasks: List[Task]) -> None:
        self._system.push_back(tasks)

    def _local(self, stream):
        return self._queues[stream.th_id]

    def _steal_order(self, stream) -> List[int]:
        """Victims by increasing topological distance: ring order, same
        virtual process (NUMA-ish group) first — the hwloc-distance walk of
        flow_*_init (sched_lfq_module.c / sched.h:210-335). Computed once
        per stream (the stream set is fixed after Context init) — this
        runs on every idle-spin select()."""
        me = stream.th_id
        cached = self._steal_cache.get(me)
        if cached is not None and len(cached) == len(self._order) - 1:
            return cached
        n = len(self._order)
        if n <= 1:
            return []
        start = self._order.index(me) if me in self._order else 0
        order = [self._order[(start + d) % n] for d in range(1, n)]
        my_vp = getattr(stream, "vp_id", 0)
        # sort victims by (same-VP first, NUMA core distance, ring order —
        # the stable sort preserves ring position as the final tiebreak):
        # the hwloc-distance steal walk of the reference's flow_init
        vmap = getattr(self.context, "vpmap", None)
        if vmap is not None:
            from .vpmap import core_distance_fn
            dist = core_distance_fn()
            my_core = vmap.core_of(me)
            order.sort(key=lambda tid: (
                0 if self.context.streams[tid].vp_id == my_vp else 1,
                dist(my_core, vmap.core_of(tid))))
        else:
            order.sort(key=lambda tid: 0 if
                       self.context.streams[tid].vp_id == my_vp else 1)
        self._steal_cache[me] = order
        return order

    def stats(self, stream):
        return {"local_len": len(self._local(stream)),
                "system_len": len(self._system)}

    def stats_global(self):
        local = sum(len(q) for q in self._queues.values())
        system = len(self._system)
        return {"queued": local + system, "local_len": local,
                "system_len": system}

    def has_local_work(self, stream) -> bool:
        return bool(len(self._local(stream)) or len(self._system))


# ---------------------------------------------------------------------------
# modules
# ---------------------------------------------------------------------------

class SchedLFQ(_LocalQueuesBase):
    """Local flat queues (default): per-stream bounded buffer (cap 4·ncores)
    spilling straight to the shared system dequeue; distance-ordered steal
    (ref: parsec/mca/sched/lfq/sched_lfq_module.c:73, hbbuffer.c)."""
    name = "lfq"
    priority = 20
    native_policy = "wdrr"

    def flow_init(self, stream) -> None:
        # bounded per-stream buffers exist to keep work stealable: with ONE
        # stream there is nobody to steal, so spilling to the system deque
        # (and walking the empty steal order on every select) is pure cost
        # — the local buffer absorbs everything
        ns = len(self.context.streams)
        cap = 4 * ns if ns > 1 else (1 << 30)
        with self._init_lock:
            self._queues[stream.th_id] = _HBBuffer(cap, self._system_push)
            self._order.append(stream.th_id)

    def schedule(self, stream, tasks, distance: int = 0) -> None:
        tasks = list(tasks)
        if not tasks:
            return
        if distance == 0:
            self._local(stream).push(tasks)
        else:                       # pushed away from the hot end
            self._system.push_back(tasks)

    def select(self, stream):
        t = self._local(stream).pop_best()
        if t is not None:
            return t, 0
        for d, tid in enumerate(self._steal_order(stream), start=1):
            t = self._queues[tid].pop_any()
            if t is not None:
                return t, d
        return self._system.pop_front(), len(self._order)

    def select_burst(self, stream, n: int):
        batch = self._local(stream).pop_best_burst(n)
        if batch:
            return batch
        return super().select_burst(stream, n)   # steal/system path


class SchedPBQ(_LocalQueuesBase):
    """Priority-based local bounded queues: like lfq but the buffer keeps
    priority order on every push and spills its LOWEST-priority tasks to
    the system queue — hot work never leaves the owning stream
    (ref: sched_pbq, hbbuffer_push_all_by_priority)."""
    name = "pbq"
    native_policy = "prio"

    flow_init = SchedLFQ.flow_init

    def schedule(self, stream, tasks, distance: int = 0) -> None:
        tasks = list(tasks)
        if not tasks:
            return
        if distance == 0:
            self._local(stream).push_by_priority(tasks)
        else:
            self._system.push_back(tasks)

    select = SchedLFQ.select


class SchedLHQ(_LocalQueuesBase):
    """Local hierarchical queues: stream buffer -> shared per-VP buffer ->
    system dequeue; overflow climbs the hierarchy level by level and select
    walks it back down before crossing to other VPs
    (ref: sched_lhq_module.c, nested hbbuffers per hwloc level)."""
    name = "lhq"
    native_policy = "wdrr"

    def install(self, context) -> None:
        super().install(context)
        self._vp_queues: Dict[int, _HBBuffer] = {}

    def flow_init(self, stream) -> None:
        vp = getattr(stream, "vp_id", 0)
        with self._init_lock:
            vq = self._vp_queues.get(vp)
            if vq is None:
                nvp_cores = max(1, sum(
                    1 for s in self.context.streams if s.vp_id == vp))
                vq = _HBBuffer(max(96 // nvp_cores, nvp_cores),
                               self._system_push)
                self._vp_queues[vp] = vq
            self._queues[stream.th_id] = _HBBuffer(
                4 * max(1, len(self.context.streams)), vq.push)
            self._order.append(stream.th_id)

    def schedule(self, stream, tasks, distance: int = 0) -> None:
        tasks = list(tasks)
        if not tasks:
            return
        if distance == 0:
            self._local(stream).push(tasks)
        elif distance == 1:
            self._vp_queues[getattr(stream, "vp_id", 0)].push(tasks)
        else:
            self._system.push_back(tasks)

    def select(self, stream):
        t = self._local(stream).pop_best()
        if t is not None:
            return t, 0
        my_vp = getattr(stream, "vp_id", 0)
        t = self._vp_queues[my_vp].pop_best()
        if t is not None:
            return t, 1
        d = 1
        for tid in self._steal_order(stream):
            if self.context.streams[tid].vp_id == my_vp:
                d += 1
                t = self._queues[tid].pop_any()
                if t is not None:
                    return t, d
        for vp, vq in self._vp_queues.items():
            if vp != my_vp:
                d += 1
                t = vq.pop_any()
                if t is not None:
                    return t, d
        for tid in self._steal_order(stream):
            if self.context.streams[tid].vp_id != my_vp:
                d += 1
                t = self._queues[tid].pop_any()
                if t is not None:
                    return t, d
        return self._system.pop_front(), d + 1

    def stats(self, stream):
        s = super().stats(stream)
        s["vp_len"] = len(self._vp_queues.get(getattr(stream, "vp_id", 0), ()))
        return s


class _TaskHeap:
    """A group of related ready tasks as one schedulable unit, ordered by
    priority (redesign of parsec_heap_t, parsec/maxheap.c:1-385)."""

    __slots__ = ("heap", "_ctr")

    def __init__(self, tasks: List[Task]) -> None:
        self._ctr = itertools.count()
        self.heap = [(-t.priority, next(self._ctr), t) for t in tasks]
        heapq.heapify(self.heap)

    @property
    def top_priority(self) -> int:
        return -self.heap[0][0] if self.heap else -(1 << 62)

    def pop(self) -> Optional[Task]:
        return heapq.heappop(self.heap)[2] if self.heap else None

    def split(self) -> Optional["_TaskHeap"]:
        """Give away about half the tasks (heap_split_and_steal): the thief
        walks off with a subtree, keeping sibling groups together."""
        if len(self.heap) < 2:
            return None
        self.heap.sort()
        mine, theirs = self.heap[::2], self.heap[1::2]
        self.heap = mine
        heapq.heapify(self.heap)
        other = _TaskHeap([])
        other.heap = theirs
        heapq.heapify(other.heap)
        return other

    def __len__(self) -> int:
        return len(self.heap)


class SchedLTQ(_LocalQueuesBase):
    """Local tree queues: every schedule() call becomes ONE heap of tasks;
    streams pop the top of their best heap and keep the rest; a steal takes
    the victim's best heap and SPLITS it, carrying half home — related
    tasks migrate together (ref: sched_ltq_module.c + maxheap.c)."""
    name = "ltq"
    native_policy = "prio"

    def flow_init(self, stream) -> None:
        with self._init_lock:
            self._queues[stream.th_id] = _LockedHeapList()
            self._order.append(stream.th_id)

    def schedule(self, stream, tasks, distance: int = 0) -> None:
        tasks = list(tasks)
        if not tasks:
            return
        self._local(stream).add(_TaskHeap(tasks))

    def select(self, stream):
        own: _LockedHeapList = self._local(stream)
        t = own.pop_task()
        if t is not None:
            return t, 0
        for d, tid in enumerate(self._steal_order(stream), start=1):
            victim: _LockedHeapList = self._queues[tid]
            stolen = victim.steal_half()
            if stolen is not None:
                t = stolen.pop()
                if len(stolen):
                    own.add(stolen)
                if t is not None:
                    return t, d
        return None, 0

    def stats(self, stream):
        q = self._local(stream)
        return {"local_heaps": len(q.heaps),
                "local_len": sum(len(h) for h in q.heaps)}


class _LockedHeapList:
    """Per-stream list of _TaskHeaps (the hbbuffer-of-heaps of ltq)."""

    __slots__ = ("heaps", "lock")

    def __init__(self) -> None:
        self.heaps: List[_TaskHeap] = []
        self.lock = threading.Lock()

    def add(self, h: _TaskHeap) -> None:
        with self.lock:
            self.heaps.append(h)

    def pop_task(self) -> Optional[Task]:
        with self.lock:
            if not self.heaps:
                return None
            best = max(range(len(self.heaps)),
                       key=lambda i: self.heaps[i].top_priority)
            h = self.heaps[best]
            t = h.pop()
            if not len(h):
                self.heaps.pop(best)
            return t

    def steal_half(self) -> Optional[_TaskHeap]:
        with self.lock:
            if not self.heaps:
                return None
            best = max(range(len(self.heaps)),
                       key=lambda i: self.heaps[i].top_priority)
            h = self.heaps[best]
            half = h.split()
            if half is not None:
                return half
            return self.heaps.pop(best)   # singleton: take it whole

    def __len__(self) -> int:
        return len(self.heaps)


class SchedLL(_LocalQueuesBase):
    """Local LIFO: push and pop the same end (depth-first), steal the other
    (ref: sched_ll)."""
    name = "ll"
    native_policy = "fifo"

    def flow_init(self, stream) -> None:
        with self._init_lock:
            self._queues[stream.th_id] = _LockedDeque()
            self._order.append(stream.th_id)

    def schedule(self, stream, tasks, distance: int = 0) -> None:
        tasks = list(tasks)
        if tasks:
            self._local(stream).push_front(tasks)

    def select(self, stream):
        t = self._local(stream).pop_front()
        if t is not None:
            return t, 0
        for d, tid in enumerate(self._steal_order(stream), start=1):
            t = self._queues[tid].pop_back()
            if t is not None:
                return t, d
        return None, 0


class SchedLLP(_LocalQueuesBase):
    """Local LIFO with priorities: an UNBOUNDED per-stream list kept in
    priority order (LIFO among equals — latest insert at the head of its
    priority class); no system queue; thieves take from the cold end
    (ref: sched_llp, parsec_lifo_with_prio)."""
    name = "llp"
    native_policy = "prio"

    def flow_init(self, stream) -> None:
        with self._init_lock:
            self._queues[stream.th_id] = _PrioLIFO()
            self._order.append(stream.th_id)

    def schedule(self, stream, tasks, distance: int = 0) -> None:
        tasks = list(tasks)
        if tasks:
            self._local(stream).push(tasks)

    def select(self, stream):
        t = self._local(stream).pop_head()
        if t is not None:
            return t, 0
        for d, tid in enumerate(self._steal_order(stream), start=1):
            t = self._queues[tid].pop_tail()
            if t is not None:
                return t, d
        return None, 0


class _PrioLIFO:
    """Priority-ordered LIFO (redesign of parsec_lifo_with_prio): head =
    highest priority, newest first within a priority class."""

    __slots__ = ("items", "lock")

    def __init__(self) -> None:
        self.items: List[Task] = []   # descending priority
        self.lock = threading.Lock()

    def push(self, tasks: List[Task]) -> None:
        with self.lock:
            keys = [-t.priority for t in self.items]
            for t in tasks:
                i = bisect.bisect_left(keys, -t.priority)
                self.items.insert(i, t)
                keys.insert(i, -t.priority)

    def pop_head(self) -> Optional[Task]:
        with self.lock:
            return self.items.pop(0) if self.items else None

    def pop_tail(self) -> Optional[Task]:
        with self.lock:
            return self.items.pop() if self.items else None

    def __len__(self) -> int:
        return len(self.items)


class _GlobalBase(SchedulerModule):
    def install(self, context) -> None:
        super().install(context)
        self._q = _LockedDeque()

    def flow_init(self, stream) -> None:
        pass

    def stats_global(self):
        return {"queued": len(self._q)}

    def has_local_work(self, stream) -> bool:
        return len(self._q) > 0


class SchedGD(_GlobalBase):
    """Global dequeue (ref: sched_gd)."""
    name = "gd"
    native_policy = "fifo"

    def schedule(self, stream, tasks, distance: int = 0) -> None:
        tasks = list(tasks)
        if not tasks:
            return
        if distance == 0:
            self._q.push_front(tasks)
        else:
            self._q.push_back(tasks)

    def select(self, stream):
        return self._q.pop_front(), 0


class SchedRND(_GlobalBase):
    """Random order global queue (ref: sched_rnd)."""
    name = "rnd"
    native_policy = "rndsteal"

    def install(self, context) -> None:
        super().install(context)
        self._rng = random.Random(0xC0FFEE)
        # random-position inserts are compound ops; _LockedDeque itself is
        # lock-free (single GIL-atomic calls), so this module keeps its own
        self._rnd_lock = threading.Lock()

    def schedule(self, stream, tasks, distance: int = 0) -> None:
        tasks = list(tasks)
        with self._rnd_lock:
            for t in tasks:
                if self._q.dq and self._rng.random() < 0.5:
                    self._q.dq.insert(self._rng.randrange(len(self._q.dq) + 1), t)
                else:
                    self._q.dq.append(t)

    def select(self, stream):
        return self._q.pop_front(), 0


class _GlobalHeapBase(SchedulerModule):
    sign = -1           # -1: highest priority first
    tie_lifo = False    # FIFO among equal priorities

    def install(self, context) -> None:
        super().install(context)
        self._heap = _LockedHeap()

    def flow_init(self, stream) -> None:
        pass

    def stats_global(self):
        return {"queued": len(self._heap)}

    def has_local_work(self, stream) -> bool:
        return len(self._heap) > 0

    def schedule(self, stream, tasks, distance: int = 0) -> None:
        for t in tasks:
            self._heap.push(t, self.sign, self.tie_lifo)

    def select(self, stream):
        return self._heap.pop(), 0


class SchedAP(_GlobalHeapBase):
    """Absolute priority (ref: sched_ap): depth-first (LIFO) among equal
    priorities — the freshest ready task continues the critical path."""
    name = "ap"
    native_policy = "prio"
    tie_lifo = True


class SchedSPQ(_GlobalHeapBase):
    """Shared priority queue (ref: sched_spq)."""
    name = "spq"
    native_policy = "prio"


class SchedIP(_GlobalHeapBase):
    """Inverse priority (ref: sched_ip): lowest priority first."""
    name = "ip"
    sign = 1
    native_policy = None   # inverse priority has no native flavor


_modules = {
    cls.name: cls
    for cls in (SchedLFQ, SchedGD, SchedLTQ, SchedLHQ, SchedAP, SchedPBQ,
                SchedIP, SchedLL, SchedLLP, SchedRND, SchedSPQ)
}


def create(name: Optional[str] = None) -> SchedulerModule:
    """MCA-style component selection (ref: parsec_set_scheduler, scheduling.c:249)."""
    name = name or mca.get("sched", "lfq")
    if name not in _modules:
        output.fatal(f"unknown scheduler module {name!r} (have: {sorted(_modules)})")
    return _modules[name]()


def available() -> List[str]:
    return sorted(_modules)
