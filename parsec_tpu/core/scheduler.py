"""Pluggable ready-queue scheduling modules.

Re-design of parsec/mca/sched (module interface: parsec/mca/sched/sched.h:210-335).
A scheduler module provides ``install / flow_init / schedule / select / remove``;
``schedule`` receives a *distance* hint conveying steal/locality distance exactly
as in the reference. The module is selected at runtime through the MCA parameter
``sched`` (ref: parsec_set_scheduler, parsec/scheduling.c:249-275).

Module set mirrors the reference's (parsec/mca/sched/*):

=========  =====================================================================
``lfq``    local flat queues + hierarchical bounded buffers + work stealing
           (default, priority 20; ref: sched_lfq_component.c:73)
``gd``     single global dequeue (sched_gd)
``ltq``    local tree queues (approximated: local heaps, subtree-biased steal)
``lhq``    local hierarchical queues
``ap``     absolute priority: one global priority heap (sched_ap)
``pbq``    priority-based local queues + steal (sched_pbq)
``ip``     inverse priority (sched_ip)
``ll``     local LIFO + steal (sched_ll)
``llp``    local LIFO with priorities (sched_llp)
``rnd``    random global queue (sched_rnd)
``spq``    shared priority queue (sched_spq)
=========  =====================================================================

On TPU the scheduler's job is mostly *dispatch ordering*: bodies are issued
asynchronously to the device stream, so queue policy governs pipeline depth and
data locality (which tiles stay HBM-resident), not CPU load balance.
"""

from __future__ import annotations

import heapq
import itertools
import random
import threading
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

from ..utils import mca, output
from .task import Task

mca.register("sched", "lfq", "Scheduler module (lfq|gd|ltq|lhq|ap|pbq|ip|ll|llp|rnd|spq)")


class SchedulerModule:
    """Module interface (ref: parsec/mca/sched/sched.h:210-335)."""

    name = "base"
    priority = 0  # component selection priority, highest wins

    def install(self, context) -> None:
        self.context = context

    def flow_init(self, stream) -> None:
        """Per-execution-stream initialization (ref: flow_init + barrier)."""

    def schedule(self, stream, tasks: Iterable[Task], distance: int = 0) -> None:
        raise NotImplementedError

    def select(self, stream) -> Tuple[Optional[Task], int]:
        """Return (task, distance-it-came-from) or (None, 0)."""
        raise NotImplementedError

    def stats(self, stream) -> Dict[str, int]:
        return {}

    def remove(self, context) -> None:
        pass


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

class _LockedDeque:
    __slots__ = ("dq", "lock")

    def __init__(self) -> None:
        self.dq: deque = deque()
        self.lock = threading.Lock()

    def push_front(self, items) -> None:
        with self.lock:
            self.dq.extendleft(reversed(items))

    def push_back(self, items) -> None:
        with self.lock:
            self.dq.extend(items)

    def pop_front(self):
        with self.lock:
            return self.dq.popleft() if self.dq else None

    def pop_back(self):
        with self.lock:
            return self.dq.pop() if self.dq else None

    def __len__(self) -> int:
        return len(self.dq)


class _LockedHeap:
    """Priority heap; highest priority pops first (ties FIFO)."""

    __slots__ = ("heap", "lock", "_ctr")

    def __init__(self) -> None:
        self.heap: List = []
        self.lock = threading.Lock()
        self._ctr = itertools.count()

    def push(self, task: Task, sign: int = -1) -> None:
        with self.lock:
            heapq.heappush(self.heap, (sign * task.priority, next(self._ctr), task))

    def pop(self) -> Optional[Task]:
        with self.lock:
            if not self.heap:
                return None
            return heapq.heappop(self.heap)[2]

    def __len__(self) -> int:
        return len(self.heap)


# ---------------------------------------------------------------------------
# modules
# ---------------------------------------------------------------------------

class _LocalQueuesBase(SchedulerModule):
    """Shared shape for per-stream-queue + steal modules
    (ref: parsec/mca/sched/sched_local_queues_utils.h)."""

    lifo = False         # pop same end we push (depth-first) vs FIFO
    use_priority = False

    def install(self, context) -> None:
        super().install(context)
        self._queues: Dict[int, object] = {}
        self._order: List[int] = []

    def flow_init(self, stream) -> None:
        q = _LockedHeap() if self.use_priority else _LockedDeque()
        self._queues[stream.th_id] = q
        self._order.append(stream.th_id)

    def _local(self, stream):
        return self._queues[stream.th_id]

    def schedule(self, stream, tasks, distance: int = 0) -> None:
        tasks = list(tasks)
        if not tasks:
            return
        # distance>0 pushes away from the hot end, as hbbuffer does in the
        # reference (parsec/hbbuffer.c): locality hint, not a strict target.
        q = self._local(stream)
        if self.use_priority:
            for t in tasks:
                q.push(t)
        elif distance == 0:
            q.push_front(tasks)
        else:
            q.push_back(tasks)

    def select(self, stream):
        q = self._local(stream)
        t = q.pop() if self.use_priority else q.pop_front()
        if t is not None:
            return t, 0
        # work stealing by increasing topological distance: same virtual
        # process (NUMA-ish group) first, then the rest — the hierarchy the
        # reference's lfq walks through its bounded buffers
        me = stream.th_id
        n = len(self._order)
        if n > 1:
            my_vp = getattr(stream, "vp_id", 0)
            ctx = getattr(self, "context", None)
            start = self._order.index(me) if me in self._order else 0
            order = [self._order[(start + d) % n] for d in range(1, n)]
            if ctx is not None:
                order.sort(key=lambda tid: 0 if
                           ctx.streams[tid].vp_id == my_vp else 1)
            for d, tid in enumerate(order, start=1):
                victim = self._queues[tid]
                t = victim.pop() if self.use_priority else victim.pop_back()
                if t is not None:
                    return t, d
        return None, 0

    def stats(self, stream):
        return {"local_len": len(self._local(stream))}


class SchedLFQ(_LocalQueuesBase):
    """Local flat queues (default; ref: parsec/mca/sched/lfq/sched_lfq_module.c)."""
    name = "lfq"
    priority = 20


class SchedLL(_LocalQueuesBase):
    """Local LIFO (ref: sched_ll): always push and pop the front (depth-first)."""
    name = "ll"

    def schedule(self, stream, tasks, distance: int = 0) -> None:
        tasks = list(tasks)
        if tasks:
            self._local(stream).push_front(tasks)


class SchedLLP(_LocalQueuesBase):
    """Local LIFO with priorities (ref: sched_llp, 657 LoC)."""
    name = "llp"
    use_priority = True


class SchedPBQ(_LocalQueuesBase):
    """Priority-based local queues (ref: sched_pbq)."""
    name = "pbq"
    use_priority = True


class SchedLTQ(_LocalQueuesBase):
    """Local tree queues: heap-ordered local queues, nearest-neighbor steal
    (ref: sched_ltq uses maxheaps per thread, parsec/maxheap.c)."""
    name = "ltq"
    use_priority = True


class SchedLHQ(_LocalQueuesBase):
    """Local hierarchical queues (ref: sched_lhq): per-thread queues with
    hierarchy-ordered stealing; hierarchy degenerates to ring order here."""
    name = "lhq"


class _GlobalBase(SchedulerModule):
    def install(self, context) -> None:
        super().install(context)
        self._q = _LockedDeque()

    def flow_init(self, stream) -> None:
        pass


class SchedGD(_GlobalBase):
    """Global dequeue (ref: sched_gd)."""
    name = "gd"

    def schedule(self, stream, tasks, distance: int = 0) -> None:
        tasks = list(tasks)
        if not tasks:
            return
        if distance == 0:
            self._q.push_front(tasks)
        else:
            self._q.push_back(tasks)

    def select(self, stream):
        return self._q.pop_front(), 0


class SchedRND(_GlobalBase):
    """Random order global queue (ref: sched_rnd)."""
    name = "rnd"

    def install(self, context) -> None:
        super().install(context)
        self._rng = random.Random(0xC0FFEE)

    def schedule(self, stream, tasks, distance: int = 0) -> None:
        tasks = list(tasks)
        with self._q.lock:
            for t in tasks:
                if self._q.dq and self._rng.random() < 0.5:
                    self._q.dq.insert(self._rng.randrange(len(self._q.dq) + 1), t)
                else:
                    self._q.dq.append(t)

    def select(self, stream):
        return self._q.pop_front(), 0


class _GlobalHeapBase(SchedulerModule):
    sign = -1  # -1: highest priority first

    def install(self, context) -> None:
        super().install(context)
        self._heap = _LockedHeap()

    def flow_init(self, stream) -> None:
        pass

    def schedule(self, stream, tasks, distance: int = 0) -> None:
        for t in tasks:
            self._heap.push(t, self.sign)

    def select(self, stream):
        return self._heap.pop(), 0


class SchedAP(_GlobalHeapBase):
    """Absolute priority (ref: sched_ap)."""
    name = "ap"


class SchedSPQ(_GlobalHeapBase):
    """Shared priority queue (ref: sched_spq)."""
    name = "spq"


class SchedIP(_GlobalHeapBase):
    """Inverse priority (ref: sched_ip): lowest priority first."""
    name = "ip"
    sign = 1


_modules = {
    cls.name: cls
    for cls in (SchedLFQ, SchedGD, SchedLTQ, SchedLHQ, SchedAP, SchedPBQ,
                SchedIP, SchedLL, SchedLLP, SchedRND, SchedSPQ)
}


def create(name: Optional[str] = None) -> SchedulerModule:
    """MCA-style component selection (ref: parsec_set_scheduler, scheduling.c:249)."""
    name = name or mca.get("sched", "lfq")
    if name not in _modules:
        output.fatal(f"unknown scheduler module {name!r} (have: {sorted(_modules)})")
    return _modules[name]()


def available() -> List[str]:
    return sorted(_modules)
