"""Python lifecycle of the native multi-pool scheduler plane (ISSUE 9).

The C machinery lives in ``native/src/ptsched.h`` (per-worker bounded hot
queues, steal-half work stealing, per-pool overflow heaps, weighted
deficit-round-robin arbitration, admission windows); this module owns the
plane per :class:`~parsec_tpu.core.context.Context`:

* **creation** — :meth:`SchedPlane.maybe_create` arms one plane per
  context when the native module loads AND the selected scheduler module
  maps to a native arbitration flavor
  (:attr:`~parsec_tpu.core.scheduler.SchedulerModule.native_policy`);
  a policy without a native analogue (e.g. ``ip``) counts an honest
  ``policy_fallback`` and every pool stays on its private ready
  structure — exactly the engagement-counter contract of the lanes;
* **pool registry** — taskpools register with a QoS weight
  (``tp.qos_weight`` or ``--mca sched_pool_weight``) and an admission
  window (``tp.admission_window`` or ``--mca sched_admission_window``);
  the handle routes their ready tasks through the plane (ptexec:
  ``Graph.sched_bind``; DTD: ``Engine.register_class(..., pool=h)``);
* **counters** — ``sched.*`` in the unified registry (steals, spills,
  per-plane served/queued, admission stalls, engagement splits) plus the
  ``sched.queue_ns`` push->pop wait histogram (utils/hist.py kind
  ``sched``), sampled across every live plane like the ptcomm wire
  counters.

See docs/scheduling.md for the policy matrix and the weight math.
"""

from __future__ import annotations

import threading
import time
import weakref
import zlib
from typing import Dict, Optional

from ..utils import mca, output
from ..utils.counters import LaneStats

mca.register("sched_native", True,
             "Arm the native multi-pool scheduler plane (ptsched) when "
             "the selected scheduler module has a native arbitration "
             "flavor; 0 keeps every engine on its private ready structure",
             type=bool)
mca.register("sched_pool_weight", 1,
             "Default QoS weight of a taskpool on the scheduler plane "
             "(DRR share: a weight-2 pool is served ~2x the tasks of a "
             "weight-1 pool under contention); per-pool override via "
             "tp.qos_weight", type=int)
mca.register("sched_quantum", 256,
             "DRR credit unit of the scheduler plane (tasks per weight "
             "point per round). Weights only bind on pools whose backlog "
             "exceeds weight*quantum, so serving meshes with tight "
             "admission windows (ptfab) want a SMALL quantum — the "
             "fairness/batching tradeoff documented in docs/serving.md",
             type=int)
mca.register("sched_admission_window", 0,
             "Admission soft limit per taskpool (in-flight inserted-but-"
             "not-completed tasks) on the scheduler plane: past it, "
             "insert_task blocks (helping drain) or raises with "
             "nowait=True. 0 = unlimited; per-pool override via "
             "tp.admission_window", type=int)

#: engagement counters (the honest-fallback contract of the lanes):
#: ``pools_engaged`` counts pools whose ready structure moved into the
#: plane, ``pools_retired`` the ones that completed and freed their slot,
#: ``policy_fallback`` contexts whose --mca sched flavor has no native
#: analogue (pools then ride the interpreted/private paths by design),
#: ``admission_stalls``/``admission_rejects`` the backpressure outcomes.
SCHED_STATS = LaneStats(pools_engaged=0, pools_retired=0,
                        policy_fallback=0, plane_unavailable=0,
                        admission_stalls=0, admission_rejects=0)

#: plane-level C counters exported as ``sched.<key>`` (summed over live
#: planes, the ptcomm wire-counter pattern). ``admission_stalls`` is NOT
#: here: SCHED_STATS exports it under the same name with process
#: lifetime (count_stall bumps both), and registering the live-planes
#: sampler too would shadow it — a finished context's stalls would then
#: read 0 the moment its plane is collected.
PLANE_COUNTER_KEYS = ("steals", "steal_visits", "spills", "served",
                      "queued", "pools_live")

_live_planes: "weakref.WeakSet" = weakref.WeakSet()
_live_lock = threading.Lock()


def plane_counter_sampler(key: str):
    """A registry sampler summing one plane stat over live planes (the
    short-TTL snapshot means one registry sweep costs one stats() call
    per plane, not one per counter key — the comm/device lane idiom)."""
    def sample():
        total = 0
        with _live_lock:
            planes = list(_live_planes)
        for sp in planes:
            try:
                total += sp.stats_cached().get(key, 0)
            except Exception:  # noqa: BLE001 — a torn-down plane
                pass
        return total
    return sample


class SchedPlane:
    """One native scheduler plane bound to one Context."""

    def __init__(self, mod, nworkers: int, policy_name: str) -> None:
        self.mod = mod
        self.policy = policy_name
        self.plane = mod.Plane(
            nworkers=nworkers,
            policy=getattr(mod, f"POLICY_{policy_name.upper()}"),
            quantum=max(1, int(mca.get("sched_quantum", 256))))
        #: the capsule the engines bind through (owns a plane ref)
        self.capsule = self.plane.plane_capsule()
        self.KIND_PTEXEC = mod.KIND_PTEXEC
        self.KIND_PTDTD = mod.KIND_PTDTD
        self.KIND_EXT = mod.KIND_EXT
        self._pools: Dict[int, str] = {}       # handle -> pool name
        self._lock = threading.Lock()
        self._stats_cache: tuple = (0.0, None)  # (stamp, snapshot)
        with _live_lock:
            _live_planes.add(self)

    # ------------------------------------------------------------- creation
    @classmethod
    def maybe_create(cls, context) -> Optional["SchedPlane"]:
        """The context-init gate: native module + native-eligible policy.
        Declines are COUNTED (SCHED_STATS), never silent."""
        if not mca.get("sched_native", True):
            return None
        policy = getattr(context.sched, "native_policy", None)
        if policy is None:
            # the selected --mca sched flavor has no native analogue
            # (e.g. ip): honest fallback, interpreted ordering preserved
            SCHED_STATS["policy_fallback"] += 1
            return None
        from .. import native as native_mod
        mod = native_mod.load_ptsched()
        if mod is None:
            SCHED_STATS["plane_unavailable"] += 1
            return None
        sp = cls(mod, context.nb_cores, policy)
        output.debug_verbose(2, "sched",
                             f"scheduler plane up: policy={policy}, "
                             f"{context.nb_cores} workers")
        return sp

    # ------------------------------------------------------------ pools
    def register_pool(self, name: str, kind: int,
                      weight: Optional[int] = None,
                      window: Optional[int] = None) -> int:
        """Admit a taskpool; returns its plane handle, or -1 when the
        pool table is full (the caller stays on its private structure)."""
        w = weight if weight else mca.get("sched_pool_weight", 1)
        win = window if window is not None \
            else mca.get("sched_admission_window", 0)
        try:
            h = self.plane.register_pool(
                ext_id=zlib.crc32(name.encode()) & 0xFFFFFFFF,
                kind=kind, weight=max(1, int(w)), window=max(0, int(win)))
        except RuntimeError:
            return -1
        with self._lock:
            self._pools[h] = name
        SCHED_STATS["pools_engaged"] += 1
        return h

    def unregister_pool(self, h: Optional[int]) -> None:
        if h is None or h < 0:
            return
        with self._lock:
            known = self._pools.pop(h, None)
        if known is None:
            return          # already freed (idempotent retire paths)
        self.plane.unregister_pool(h)
        SCHED_STATS["pools_retired"] += 1

    def forget_pool(self, h: Optional[int]) -> None:
        """Drop the name mapping for a slot whose NATIVE free belongs to
        someone else (a sched-bound ptexec graph frees its own slot in
        sched_unbind/dealloc — a second native free here could kill an
        unrelated pool that reused the slot)."""
        if h is None or h < 0:
            return
        with self._lock:
            if self._pools.pop(h, None) is not None:
                SCHED_STATS["pools_retired"] += 1

    def pool_name(self, h: int) -> Optional[str]:
        with self._lock:
            return self._pools.get(h)

    # ------------------------------------------------------- arbitration
    def next_ptexec(self):
        """DRR pick among registered ptexec pools with queued work:
        (handle, quantum) or None. The context's lane drain uses this to
        choose WHICH graph a worker serves next and for how many credits
        (charge() spends them back)."""
        return self.plane.next_pool(self.KIND_PTEXEC)

    def charge(self, h: int, n: int) -> None:
        self.plane.charge(h, n)

    def queued_total(self) -> int:
        """Ready items across every live pool — the starvation-backoff
        consult: a worker must not park while ANY pool holds spill."""
        return self.plane.queued_kind(self.mod.KIND_ANY)

    # ---------------------------------------------------------- admission
    def over_window(self, h: Optional[int]) -> bool:
        return h is not None and h >= 0 and self.plane.over_window(h)

    def count_stall(self, h: int) -> None:
        self.plane.stall(h)
        SCHED_STATS["admission_stalls"] += 1

    # ------------------------------------------------- serving fabric
    # (ptfab, ISSUE 11): remote-window reservations + the mid-run QoS
    # weight nudge the reconciliation loop applies. All thin passthroughs
    # to the native plane — the fabric holds handles, not pool names.
    def headroom(self, h: Optional[int]) -> int:
        """Grantable window room of pool h (-1 = unlimited)."""
        if h is None or h < 0:
            return 0
        return self.plane.headroom(h)

    def remote_grant(self, h: int, n: int = 1) -> None:
        self.plane.remote_grant(h, n)

    def remote_release(self, h: int, n: int = 1) -> None:
        self.plane.remote_release(h, n)

    def set_weight(self, h: int, weight: int) -> None:
        self.plane.set_weight(h, max(1, int(weight)))

    def admit(self, h: int, n: int = 1) -> None:
        self.plane.admit(h, n)

    def retired(self, h: int, n: int = 1) -> None:
        self.plane.retired(h, n)

    # ------------------------------------------------------------- stats
    def stats(self) -> Dict[str, int]:
        return self.plane.stats()

    def stats_cached(self, ttl: float = 0.02) -> Dict[str, int]:
        """:meth:`stats` behind a short TTL — one registry sweep (6
        ``sched.*`` sampler keys) pays one native stats() call, not 6."""
        now = time.monotonic()
        stamp, snap = self._stats_cache
        if snap is None or now - stamp > ttl:
            snap = self.plane.stats()
            self._stats_cache = (now, snap)
        return snap

    def pool_stats(self, h: int) -> Dict[str, int]:
        return self.plane.pool_stats(h)
