"""PINS: performance instrumentation callback chain.

Re-design of parsec/mca/pins (events: parsec/mca/pins/pins.h:26-55). Modules
register callbacks per lifecycle event; the runtime fires them at the same
points the reference does (e.g. EXEC_BEGIN/END inside __parsec_execute,
scheduling.c:185-192). Fan-out is a simple chain per event, like the
reference's linked callback lists.
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable, Dict, List

from ..utils import mca

mca.register(
    "pins_paranoid", False,
    "Full-fidelity instrumentation: force instrumented pools OFF the "
    "native execution lanes so every task pays the per-task Python PINS "
    "cycle (the pre-PR5 observer behavior — ~100x slower, but every "
    "callback fires per task). Default off: native lanes stay engaged "
    "under profiling and record in-lane ring events instead "
    "(utils/native_trace.py), so the trace describes the machine that "
    "actually runs in production", type=bool)

# Event names (ref: PINS_FLAG enum, parsec/mca/pins/pins.h:26-55)
SELECT_BEGIN = "select_begin"
SELECT_END = "select_end"
PREPARE_INPUT_BEGIN = "prepare_input_begin"
PREPARE_INPUT_END = "prepare_input_end"
RELEASE_DEPS_BEGIN = "release_deps_begin"
RELEASE_DEPS_END = "release_deps_end"
ACTIVATE_CB_BEGIN = "activate_cb_begin"
ACTIVATE_CB_END = "activate_cb_end"
DATA_FLUSH_BEGIN = "data_flush_begin"
DATA_FLUSH_END = "data_flush_end"
EXEC_BEGIN = "exec_begin"
EXEC_END = "exec_end"
COMPLETE_EXEC_BEGIN = "complete_exec_begin"
COMPLETE_EXEC_END = "complete_exec_end"
SCHEDULE_BEGIN = "schedule_begin"
SCHEDULE_END = "schedule_end"

ALL_EVENTS = [
    SELECT_BEGIN, SELECT_END, PREPARE_INPUT_BEGIN, PREPARE_INPUT_END,
    RELEASE_DEPS_BEGIN, RELEASE_DEPS_END, ACTIVATE_CB_BEGIN, ACTIVATE_CB_END,
    DATA_FLUSH_BEGIN, DATA_FLUSH_END, EXEC_BEGIN, EXEC_END,
    COMPLETE_EXEC_BEGIN, COMPLETE_EXEC_END, SCHEDULE_BEGIN, SCHEDULE_END,
]


class PinsManager:
    """Per-context PINS registry (ref: PARSEC_PINS_INIT, parsec/parsec.c:845)."""

    def __init__(self) -> None:
        self._chains: Dict[str, List[Callable]] = {e: [] for e in ALL_EVENTS}
        self._lock = threading.Lock()
        self.enabled = False
        #: True when instrumentation must eject pools from the native
        #: lanes (``enabled`` and ``--mca pins_paranoid 1``). This — not
        #: ``enabled`` — is what the lane-eligibility gates consult:
        #: plain profiling keeps the hot path native (in-lane ring
        #: tracing covers it) so the recorded trace has no observer
        #: effect. Cached as a plain attribute because the DTD per-task
        #: progress path reads it per task; recomputed when a callback
        #: registers (the only way ``enabled`` flips) and when the mca
        #: param changes.
        self.paranoid = False
        ref = weakref.ref(self)

        def _recompute(_value=None, _ref=ref):
            m = _ref()
            if m is not None:
                m.paranoid = m.enabled and mca.get("pins_paranoid", False)

        self._recompute_paranoid = _recompute
        mca.params.on_change("pins_paranoid", _recompute)
        _recompute()

    def register(self, event: str, cb: Callable) -> None:
        """PARSEC_PINS_REGISTER: prepend cb to the event chain."""
        with self._lock:
            self._chains[event].insert(0, cb)
            self.enabled = True
        self._recompute_paranoid()

    def unregister(self, event: str, cb: Callable) -> None:
        with self._lock:
            try:
                self._chains[event].remove(cb)
            except ValueError:
                pass
            self.enabled = any(self._chains.values())
        self._recompute_paranoid()

    def fire(self, event: str, stream, task, extra=None) -> None:
        """PARSEC_PINS(...) macro equivalent; no-op when nothing registered."""
        if not self.enabled:
            return
        for cb in self._chains[event]:
            cb(stream, task, extra)
