"""Online cost models — the measurement→decision loop (ISSUE 18).

The runtime measures everything (PR 8's native histograms, the device
lane's coherency counters, the executable cache's hit accounting) but
until this module every performance decision was a static heuristic.
:class:`CostModel` turns the existing measurements into per-
``(task class, shape bucket, device)`` EWMA execute-cost estimates and
feeds three consumers:

* **device placement** (``dsl/ptg/compiler.py _ptexec_prepare``): a
  TPU-bodied class with a CPU twin is placed per-instantiation by
  measured throughput — the device-side observation stamps dispatch→
  retire wall time, so the coherency table's stage-in cost and the
  lane's poll cadence are priced in, not idealized away.  User
  ``time_estimate`` hooks seed the cold-start prior instead of
  declining lane eligibility (the PR 10 carve-out, erased).
* **fusion sizing** (``dsl/fusion.py adaptive_fusion_limits``): fuse a
  class only while its measured per-task dispatch overhead exceeds the
  fused region's marginal compiled-dispatch cost (re-trace amortized by
  the executable cache's measured reuse ratio), and split oversized
  regions at the measured break-even band instead of the static
  ``region_fusion_max``.
* **reconciler gain** (``serving/reconcile.py``): the clamped share
  multiplier's exponent adapts to measured convergence error.

Feeding discipline (the hard contract): **no new hot-path
instrumentation**.  CPU-lane observations ride the existing pthist
bump — ``native/src/ptexec.cpp`` divides the batch wall time across the
batch once per ~256 tasks and, when a cost row table is bound
(``Graph.cost_bind``), adds the same amortized per-task cost into a
per-row (count, sum) accumulator with two relaxed atomics per task.
Rows fold into this model at the SAME lifecycle points as the histogram
registry (``Context._cost_fold`` beside ``_hist_detach``).  Device-lane
observations accumulate in the dispatch/poll closures (manager thread,
no lock) and fold at the same detach.  Decisions are made at
instantiation/rebind boundaries, never per task; their cost is counted
in ``costmodel.decision_ns`` and the ci gate asserts the serving-path
share stays under 1%.

Keying: ``(class name, shape bucket, device key)``.  The shape bucket
is a log4 bucket of the pool's dominant tile byte size (4x-wide buckets
— tiles within 4x share a cost regime; :func:`shape_bucket`).  Device
keys are ``"cpu"``, ``"tpu"`` and the fused variants ``"cpu_fused"`` /
``"tpu_fused"`` (per-task cost INSIDE a fused region — what fusion
sizing compares against the unfused cost).  Two pseudo classes carry
non-execute observations through the same machinery:
``"__stage_in__"`` (H2D stage-in, bucketed by transfer size) and
``"__region_trace__"`` (region trace+compile per member, bucketed by
log2 region size band).

Persistence rides ``--mca costmodel_persist <path>`` (JSON) keyed by
:func:`~parsec_tpu.dsl.fusion.device_fingerprint` — the same key that
scopes the warm-executable cache, so a restarted serving process starts
warm; a stale fingerprint discards the file (``persist_stale``) rather
than mis-place on a different mesh.

Observability: ``costmodel.*`` in the unified registry
(utils/counters.install_native_counters) — see docs/adaptive.md.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..utils import mca, output
from ..utils.counters import LaneStats

mca.register("costmodel", True,
             "Arm the online cost models (ISSUE 18): per-(class, shape-"
             "bucket, device) EWMA execute costs folded from the native "
             "lanes' existing measurements at detach. 0 disables every "
             "adaptive consumer at once (placement, fusion sizing, "
             "reconciler gain) and skips the C-side row accumulator",
             type=bool)
mca.register("costmodel_alpha", 0.25,
             "EWMA smoothing factor per FOLD batch (not per task): "
             "new_mean*alpha + old*(1-alpha). Higher adapts faster to "
             "regime changes, lower resists noise", type=float)
mca.register("costmodel_min_count", 8,
             "Observations before a key counts as MEASURED: below this "
             "the model answers with the cold-start prior (a user "
             "time_estimate hook, when the class declares one) and "
             "decisions stay on the static heuristic")
mca.register("costmodel_placement", True,
             "Consumer (a): adaptive lane-side best-device selection — "
             "a TPU-bodied class is placed per-instantiation by measured "
             "throughput (dispatch→retire, stage-in priced in) instead "
             "of the static has-a-device-body rule. 0 restores the "
             "static heuristic while the model keeps learning", type=bool)
mca.register("costmodel_fusion", True,
             "Consumer (b): adaptive fusion sizing — fuse only while "
             "measured per-task dispatch overhead beats the fused "
             "region's marginal cost; split at the measured break-even "
             "band instead of region_fusion_max. 0 restores the static "
             "knobs", type=bool)
mca.register("costmodel_reconcile", True,
             "Consumer (c): the share reconciler's gain adapts to "
             "measured convergence error (damp on overshoot, boost on "
             "slow convergence) instead of the fixed exponent", type=bool)
mca.register("costmodel_persist", "",
             "Persist the learned cost model to this JSON path at "
             "Context.fini and load it on first use — keyed by "
             "device_fingerprint() like the warm-executable cache, so a "
             "restarted serving process starts warm (a stale fingerprint "
             "discards the file). Empty disables persistence")

#: unified-registry export (``costmodel.*``). ``decision_ns`` is the
#: cumulative wall time of every instantiation-boundary decision block —
#: the numerator of the <1% serving-path overhead contract the ci gate
#: asserts. ``placements_diverged`` counts class-placements where the
#: adaptive choice differed from the static has-a-device-body heuristic
#: (the gate requires >= 1 on the mixed DAG).
COSTMODEL_STATS = LaneStats(
    keys=0,                  # distinct (class, bucket, device) keys live
    observations=0,          # fold batches absorbed into EWMAs
    folds=0,                 # lane detach folds (C rows + device obs)
    decisions=0,             # instantiation-boundary decision blocks
    decision_ns=0,           # cumulative decision wall time
    placements_adaptive=0,   # class-placements decided by measurement
    placements_explore=0,    # cold keys probed once to learn the twin
    placements_diverged=0,   # adaptive choice != static heuristic
    fusion_sized=0,          # fusion passes with model-derived limits
    fusion_declined=0,       # classes un-fused by measured break-even
    priors_seeded=0,         # time_estimate hooks folded as priors
    gain_adapted=0,          # reconciler gain nudges
    persist_loads=0, persist_saves=0, persist_stale=0)


def shape_bucket(nbytes: int) -> int:
    """Log4 bucket of a tile/transfer byte size: sizes within 4x share
    a bucket (and hence a cost regime). 0 for unknown/empty sizes —
    still a stable key. Monotone: bigger never buckets lower."""
    if nbytes <= 0:
        return 0
    return (int(nbytes).bit_length() - 1) // 2


#: pseudo classes riding the (class, bucket, device) machinery
STAGE_IN = "__stage_in__"
REGION_TRACE = "__region_trace__"


class CostModel:
    """Process-wide online cost model: ``(class, bucket, device) ->
    [ewma_ns, count, prior_ns]`` under one lock. Every entry point is
    cheap and lock-scoped — callers sit at fold/decision boundaries,
    never in a per-task loop."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # key -> [ewma_ns, count, prior_ns-or-None]
        self._m: Dict[Tuple[str, int, str], List] = {}
        self._explored: set = set()
        self._loaded = False

    # ---------------------------------------------------------- observing
    def observe(self, cls: str, bucket: int, dev: str, mean_ns: float,
                n: int = 1) -> None:
        """Fold one batch observation (mean cost over ``n`` tasks) into
        the key's EWMA. The smoothing step is per FOLD, weighted so one
        giant lane fold converges like the many small folds it stands
        for: alpha_eff = 1 - (1-alpha)^n, capped at n>=32."""
        if n <= 0 or mean_ns < 0:
            return
        alpha = float(mca.get("costmodel_alpha", 0.25))
        a_eff = 1.0 - (1.0 - alpha) ** min(int(n), 32)
        key = (cls, int(bucket), dev)
        with self._mu:
            ent = self._m.get(key)
            if ent is None:
                self._m[key] = [float(mean_ns), int(n), None]
                COSTMODEL_STATS["keys"] = len(self._m)
            elif ent[1] == 0:
                # prior-only entry: the first MEASUREMENT initializes the
                # EWMA outright (blending from the 0.0 placeholder would
                # bias every early estimate low)
                ent[0] = float(mean_ns)
                ent[1] = int(n)
            else:
                ent[0] += a_eff * (float(mean_ns) - ent[0])
                ent[1] += int(n)
            COSTMODEL_STATS["observations"] += 1

    def seed_prior(self, cls: str, bucket: int, dev: str,
                   prior_ns: float) -> None:
        """Install a cold-start prior (a user ``time_estimate`` hook's
        answer, in ns). Never overwrites measurements; re-seeding only
        updates the prior slot."""
        key = (cls, int(bucket), dev)
        with self._mu:
            ent = self._m.get(key)
            if ent is None:
                self._m[key] = [0.0, 0, float(prior_ns)]
                COSTMODEL_STATS["keys"] = len(self._m)
            else:
                ent[2] = float(prior_ns)
            COSTMODEL_STATS["priors_seeded"] += 1

    def fold_pairs(self, items: Iterable[Tuple[Tuple[str, int, str],
                                               int, int]]) -> None:
        """Fold ``((cls, bucket, dev), count, sum_ns)`` rows — the C
        accumulator's ``cost_snapshot()`` joined with the lane's row
        metadata, and the device closures' local accumulation dicts.
        Called at lane detach (the histogram registry's lifecycle)."""
        any_row = False
        for key, cnt, sum_ns in items:
            if cnt > 0:
                any_row = True
                self.observe(key[0], key[1], key[2], sum_ns / cnt, cnt)
        if any_row:
            COSTMODEL_STATS["folds"] += 1

    # ----------------------------------------------------------- querying
    def cost(self, cls: str, bucket: int, dev: str) -> Optional[float]:
        """Best cost estimate in ns, or None when the model knows
        nothing: a MEASURED key (count >= costmodel_min_count) answers
        its EWMA; else the nearest measured bucket of the same (class,
        device) answers (4x-wide buckets — the neighbor is the right
        order of magnitude); else the prior."""
        min_count = int(mca.get("costmodel_min_count", 8))
        key = (cls, int(bucket), dev)
        with self._mu:
            ent = self._m.get(key)
            if ent is not None and ent[1] >= min_count:
                return ent[0]
            # nearest measured bucket fallback
            best = None
            for (c, b, d), e in self._m.items():
                if c == cls and d == dev and e[1] >= min_count:
                    dist = abs(b - int(bucket))
                    if best is None or dist < best[0]:
                        best = (dist, e[0])
            if best is not None:
                return best[1]
            if ent is not None and ent[2] is not None:
                return ent[2]
        return None

    def measured(self, cls: str, bucket: int, dev: str) -> bool:
        """True when the EXACT key has enough observations to trust."""
        with self._mu:
            ent = self._m.get((cls, int(bucket), dev))
            return ent is not None and \
                ent[1] >= int(mca.get("costmodel_min_count", 8))

    def begin_explore(self, cls: str, bucket: int, dev: str) -> bool:
        """One-shot exploration ticket for a cold key: the first caller
        gets True (place the class there once so the model learns the
        twin's cost), every later caller False."""
        key = (cls, int(bucket), dev)
        with self._mu:
            if key in self._explored:
                return False
            self._explored.add(key)
        COSTMODEL_STATS["placements_explore"] += 1
        return True

    def count(self, cls: str, bucket: int, dev: str) -> int:
        with self._mu:
            ent = self._m.get((cls, int(bucket), dev))
            return 0 if ent is None else ent[1]

    def snapshot(self) -> Dict[Tuple[str, int, str], Tuple[float, int,
                                                           Optional[float]]]:
        with self._mu:
            return {k: (v[0], v[1], v[2]) for k, v in self._m.items()}

    def reset(self) -> None:
        """Drop every entry and exploration ticket (bench/test
        isolation). Counters are the caller's to snapshot/delta."""
        with self._mu:
            self._m.clear()
            self._explored.clear()
            COSTMODEL_STATS["keys"] = 0

    # -------------------------------------------------------- pseudo keys
    def note_stage_in(self, dev: str, nbytes: int, ns: int) -> None:
        """One H2D stage-in observation (accumulated by the device
        dispatch closure, folded at detach via fold_pairs in practice —
        this direct entry serves tests and the interpreted path)."""
        self.observe(STAGE_IN, shape_bucket(nbytes), dev, ns, 1)

    def stage_in_ns(self, dev: str, nbytes: int) -> Optional[float]:
        return self.cost(STAGE_IN, shape_bucket(nbytes), dev)

    def note_region_trace(self, dev: str, n_members: int, ns: int) -> None:
        """One region trace+compile observation: per-MEMBER cost,
        bucketed by the log2 region-size band (trace cost per member
        grows with region size — the compile-blowup curve fusion sizing
        reads back through :func:`region_trace_ns`)."""
        if n_members <= 0:
            return
        band = max(0, int(n_members).bit_length() - 1)
        self.observe(REGION_TRACE, band, dev, ns / n_members, 1)

    def region_trace_ns(self, dev: str, n_members: int) -> Optional[float]:
        """Per-member trace cost estimate for a region of this size."""
        band = max(0, int(n_members).bit_length() - 1)
        return self.cost(REGION_TRACE, band, dev)

    # -------------------------------------------------------- persistence
    _PERSIST_VERSION = 1

    def maybe_load(self) -> None:
        """Load the persisted model once per process (first decision
        point calls this). A missing file or a stale device fingerprint
        leaves the model cold — never mis-place on a different mesh."""
        with self._mu:
            if self._loaded:
                return
            self._loaded = True
        path = mca.get("costmodel_persist", "") or ""
        if not path or not os.path.exists(path):
            return
        try:
            with open(path, "r", encoding="utf-8") as f:
                blob = json.load(f)
            from ..dsl.fusion import device_fingerprint
            if blob.get("version") != self._PERSIST_VERSION or \
                    blob.get("fingerprint") != list(device_fingerprint()):
                COSTMODEL_STATS["persist_stale"] += 1
                output.debug_verbose(
                    1, "costmodel",
                    f"discarding stale persisted model at {path} "
                    f"(fingerprint mismatch)")
                return
            with self._mu:
                for cls, bucket, dev, ewma, count, prior in \
                        blob.get("entries", ()):
                    self._m.setdefault(
                        (cls, int(bucket), dev),
                        [float(ewma), int(count),
                         None if prior is None else float(prior)])
                COSTMODEL_STATS["keys"] = len(self._m)
            COSTMODEL_STATS["persist_loads"] += 1
        except Exception as e:  # noqa: BLE001 — a warm start is advisory
            output.debug_verbose(1, "costmodel",
                                 f"persisted model load failed: {e}")

    def maybe_save(self) -> None:
        """Persist at Context.fini when ``costmodel_persist`` is set."""
        path = mca.get("costmodel_persist", "") or ""
        if not path:
            return
        try:
            from ..dsl.fusion import device_fingerprint
            with self._mu:
                entries = [[c, b, d, e[0], e[1], e[2]]
                           for (c, b, d), e in self._m.items()]
            blob = {"version": self._PERSIST_VERSION,
                    "fingerprint": list(device_fingerprint()),
                    "entries": entries}
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(blob, f)
            os.replace(tmp, path)
            COSTMODEL_STATS["persist_saves"] += 1
        except Exception as e:  # noqa: BLE001 — persistence is advisory
            output.debug_verbose(1, "costmodel",
                                 f"persisted model save failed: {e}")


def enabled() -> bool:
    """The master switch every consumer checks first."""
    return bool(mca.get("costmodel", True))


#: the process-wide model (the Context/compiler/fusion consumers all
#: feed and read this one instance; tests reset() it)
model = CostModel()


def fold_cost_rows(meta: Sequence[Tuple[str, int, str]],
                   snapshot: Sequence[Tuple[int, int]]) -> None:
    """Join a lane graph's ``cost_snapshot()`` (per-row count/sum from
    the C accumulator) with the row metadata recorded at prepare and
    fold into the model — the detach-time moment (Context._cost_fold)."""
    model.fold_pairs((meta[r], cnt, sum_ns)
                     for r, (cnt, sum_ns) in enumerate(snapshot)
                     if r < len(meta) and meta[r] is not None)
