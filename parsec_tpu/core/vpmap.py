"""Virtual-process map and thread binding.

Re-design of parsec/vpmap.c + parsec/bindthread.c + the hwloc wrapper
(parsec/parsec_hwloc.c): group worker streams into *virtual processes*
(NUMA-domain-like groups that schedulers steal within first) and bind
threads to cores. Topology discovery uses os.sched_getaffinity; binding uses
os.sched_setaffinity where the platform provides it.

Spec grammar (``--mca runtime_vpmap``), following the reference's modes:

* ``flat``           — one VP with all threads (default)
* ``rr``             — one VP per core, round-robin
* ``nb:<n>:<t>``     — n VPs with t threads each
* ``file:<path>``    — one line per VP: comma-separated core ids
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional

from ..utils import mca, output

mca.register("runtime_vpmap", "flat", "VP map spec (flat|rr|nb:<n>:<t>|file:<path>)")
mca.register("runtime_bind_threads", False, "Bind worker threads to cores", type=bool)


def available_cores() -> List[int]:
    try:
        return sorted(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        return list(range(os.cpu_count() or 1))


@dataclass
class VP:
    vp_id: int
    cores: List[int] = field(default_factory=list)

    @property
    def nb_threads(self) -> int:
        return len(self.cores)


class VPMap:
    """Ref: parsec_vpmap_init (vpmap.c)."""

    def __init__(self, spec: Optional[str] = None,
                 nb_threads: Optional[int] = None) -> None:
        spec = spec or mca.get("runtime_vpmap", "flat")
        cores = available_cores()
        if nb_threads:
            cores = (cores * ((nb_threads + len(cores) - 1) // len(cores)))[:nb_threads]
        self.vps: List[VP] = []
        if spec == "flat":
            self.vps = [VP(0, list(cores))]
        elif spec == "rr":
            self.vps = [VP(i, [c]) for i, c in enumerate(cores)]
        elif spec.startswith("nb:"):
            try:
                _, n, t = spec.split(":")
                n, t = int(n), int(t)
            except ValueError:
                output.fatal(f"bad vpmap spec {spec!r}")
            it = iter(cores * (1 + (n * t) // max(len(cores), 1)))
            self.vps = [VP(i, [next(it) for _ in range(t)]) for i in range(n)]
        elif spec.startswith("file:"):
            path = spec[5:]
            with open(path) as f:
                for i, line in enumerate(f):
                    line = line.split("#", 1)[0].strip()
                    if not line:
                        continue
                    self.vps.append(VP(len(self.vps),
                                       [int(x) for x in line.split(",")]))
        else:
            output.fatal(f"unknown vpmap spec {spec!r}")
        if not self.vps:
            self.vps = [VP(0, list(cores))]

    @property
    def nb_vps(self) -> int:
        return len(self.vps)

    @property
    def nb_threads(self) -> int:
        return sum(vp.nb_threads for vp in self.vps)

    def thread_to_vp(self, th_id: int) -> int:
        """Map a global thread id to its VP."""
        i = 0
        for vp in self.vps:
            if th_id < i + vp.nb_threads:
                return vp.vp_id
            i += vp.nb_threads
        return self.vps[-1].vp_id

    def core_of(self, th_id: int) -> int:
        i = 0
        for vp in self.vps:
            if th_id < i + vp.nb_threads:
                return vp.cores[th_id - i]
            i += vp.nb_threads
        return self.vps[-1].cores[-1]


def bind_current_thread(core: int) -> bool:
    """parsec_bindthread: pin the calling thread (best effort)."""
    try:
        os.sched_setaffinity(0, {core})
        return True
    except (AttributeError, OSError) as e:
        output.debug_verbose(2, "bindthread", f"binding to core {core} failed: {e}")
        return False
