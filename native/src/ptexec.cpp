// parsec_tpu._ptexec — the generic task FSM as a CPython extension.
//
// Stands where the reference's generated-C PTG execute path stands
// (the task FSM of parsec/scheduling.c:507-569 driven by generated
// release_deps/iterate_successors, parsec/parsec.c:1837): dependency-count
// decrement, ready-detect, dispatch, and successor release run inside ONE
// C call per *batch* of tasks. The lesson applied here is the same one the
// TPU ahead-of-time compilation line of work draws (arXiv:1810.09868):
// lowering the whole CONTROL STRUCTURE out of the interpreted host
// language — not just the task bodies — is where the order of magnitude
// lives. The Python side (dsl/ptg/compiler.py) plays jdf2c: it flattens a
// PTG taskpool's dependency structure into the CSR successor table this
// engine consumes, once per (program, globals) shape.
//
// DATA-FLOW MODE (the second lowering): a graph may additionally carry
//   * per-task priorities — the ready structure becomes a max-heap, so a
//     pop always dispatches a maximal-priority ready task;
//   * an input-slot CSR + per-slot usage limits — the datarepo retire
//     protocol (core/datarepo.py usagelmt/usagecnt) moves HERE: every
//     data flow of every task owns one slot id; consuming tasks list
//     their input slots; the release sweep decrements the slot's atomic
//     remaining-use counter and reports fully-consumed slot ids back to
//     Python, which clears the payload reference. The payloads themselves
//     never cross into C — Python owns the slot *values* (a flat list),
//     C owns the slot *lifetimes*.
// In data mode the batch callback takes TWO arguments,
// (ready_ids, retired_slot_ids); without slots it keeps the historic
// one-argument form.
//
// Concurrency contract: run() may be called from MANY Python threads on
// the same Graph. The GIL is dropped for the whole FSM walk (ready-pop,
// decrement, release) and re-acquired only to dispatch a batch of
// non-empty task bodies through the Python callback — so for empty/CTL
// task classes the walk is GIL-free end to end and Context(nb_cores>1)
// in-process workers scale on real cores. Shared state is a small mutex
// around the ready structure plus per-task (and per-slot) atomic
// counters; the release decrement uses fetch_sub so two workers
// releasing into the same successor (or retiring the same slot) can
// never double-fire it.
//
// run() never blocks waiting for work: a starved worker returns to the
// Python hot loop (which has its own backoff and other task sources) and
// comes back — the "burst handoff into/out of the lane".

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <mutex>
#include <new>
#include <vector>

#include "ptcomm_iface.h"
#include "ptdev_iface.h"
#include "pthist.h"
#include "ptrace_ring.h"
#include "ptsched.h"

namespace {

// in-lane trace event keys (registered in the PBP dictionary by
// utils/native_trace.py; see ptrace_ring.h for the ring contract)
constexpr uint32_t EV_TASK = 1;      // one interval per task's retire step
constexpr uint32_t EV_DISPATCH = 2;  // one interval per batched body dispatch
constexpr uint32_t EV_REGION = 3;    // one interval per fused-region body
                                     // (recorded via trace_mark from the
                                     // region dispatch wrapper, ISSUE 12)

// latency histogram slots (pthist.h; names mirrored in utils/hist.py)
constexpr int H_EXEC = 0;        // per-task execute latency (batch-amortized)
constexpr int H_READY = 1;       // ready-push -> pop wait (sampled 1-in-8)
constexpr int N_HISTS = 2;
const char *const HIST_NAMES[N_HISTS] = {"exec_ns", "ready_wait_ns"};
// deterministic 1-in-8 sample by task id: the armed per-task cost of the
// ready-wait histogram is one predictable branch on 7/8 of the tasks
inline bool hist_sampled(int32_t tid) { return (tid & 7) == 0; }

struct Graph {
    PyObject_HEAD
    int64_t n;
    std::vector<int32_t> *goals;     // initial dep count per task
    std::vector<int32_t> *succ_off;  // CSR offsets, n+1 entries
    std::vector<int32_t> *succs;     // flattened successor ids
    std::vector<int32_t> *seeds;     // ids with goal 0
    std::atomic<int32_t> *counts;    // remaining deps per task
    std::mutex *mu;                  // guards ready/completed/running/error
    std::vector<int32_t> *ready;     // LIFO stack, or max-heap when prio set
    int64_t completed;
    int32_t running;                 // workers mid-batch
    bool error;                      // a callback raised somewhere
    // priority mode (empty prio, use_heap=false -> plain LIFO stack)
    std::vector<int32_t> *prio;      // per-task priority
    bool use_heap;
    // data-flow mode (empty in_off -> pure control graph)
    std::vector<int32_t> *in_off;    // CSR n+1: consumed slots per task
    std::vector<int32_t> *in_slots;  // flattened input slot ids
    std::vector<int32_t> *slot_uses; // usage limit per slot (the usagelmt)
    std::atomic<int32_t> *slot_cnt;  // remaining uses (usagelmt - usagecnt)
    std::vector<int32_t> *retired;   // fully-consumed slots awaiting Python
    int64_t n_slots;
    int64_t nb_slots_retired;        // total retired (guarded by mu)
    // in-lane event rings (null until trace_enable; one relaxed check per
    // run() call when tracing never was enabled)
    std::atomic<ptrace_ring::State *> trace;
    // latency histograms (null until hist_enable; same gating discipline)
    std::atomic<pthist::State<N_HISTS> *> hist;
    // per-task ready-push timestamp for the ready-wait histogram: written
    // only when histograms are armed AND the id is sampled; atomics
    // because the comm progress thread stamps ingested tasks GIL-free
    std::atomic<int64_t> *ready_stamp;
    // distributed mode (comm_bind): per-task owner ranks; edges into a
    // non-local successor surface as activation frames on the comm lane's
    // send queue instead of local decrements, and ingest_act() lets the
    // comm progress thread drop arrived decrements straight into the
    // ready structure — both directions GIL-free (ptcomm_iface.h)
    std::vector<int32_t> *owners;     // empty = single-rank graph
    int32_t my_rank;
    uint32_t pool_id;
    bool comm_bound;
    PtCommSendVtbl send;
    int64_t n_local;                  // tasks this rank executes
    // rendezvous gates: a slot whose payload is still being pulled parks
    // would-be-ready consumers until rdv_land() (guarded by mu)
    std::vector<uint8_t> *rdv_pending;  // per input slot, 1 = pulling
    std::vector<int32_t> *parked;       // ready tasks waiting on a pull
    std::atomic<int64_t> acts_tx;       // remote releases surfaced
    std::atomic<int64_t> acts_rx;       // remote decrements ingested
    std::atomic<int64_t> ingest_bad;    // out-of-range ids from the wire
    // device lane binding (dev_bind, ISSUE 10): tasks whose class carries
    // a device body never enter the ready structure — the moment they
    // become ready (release sweep, ingest, seeding) they surface onto the
    // ptdev lane's MPSC pending queue through the submit vtable, still
    // GIL-free (ptdev_iface.h). The lane's manager thread dispatches them
    // asynchronously and lands completions back through dev_retire(),
    // which runs the release walk exactly like a local CPU retire.
    bool dev_bound;
    uint32_t dev_pool;
    PtDevSubmitVtbl dsend;
    std::vector<uint8_t> *dev_mask;   // per task: 1 = device-bodied
    std::vector<uint8_t> *dev_ret;    // per task: 1 = already retired (a
                                      // duplicate/stale retire would
                                      // double-run the release walk and
                                      // underflow successor counters)
    std::atomic<int64_t> dev_tx;      // tasks surfaced onto the lane
    std::atomic<int64_t> dev_done;    // tasks retired by the lane
    std::atomic<int64_t> dev_bad;     // out-of-range/unmasked retire ids
    // region fusion (region_bind, ISSUE 12): a fused super-task node
    // stands for `weight[i]` original tasks — the CSR already carries
    // the union of the region's external in/out edges (built by the
    // compiler's fusion pass), so the release walk crosses the seam
    // correctly by construction; the weights make the task ACCOUNTING
    // cross it too: completed/pending/done and run()'s return value
    // count original tasks, not fused nodes.
    std::vector<int32_t> *weight;     // per node; empty = all 1
    bool weighted;
    int64_t w_total;                  // sum(weight) — the done() target
    // cost-model rows (cost_bind, ISSUE 18): per-node row ids into a
    // (count, sum_ns) accumulator pair. The rows ride the SAME batch-
    // amortized clock reads as the exec_ns histogram bump — when bound,
    // each executed task adds the per-task batch cost into its row with
    // two relaxed atomics; nothing new touches the clock. Rows group
    // tasks by (class, shape bucket, device flavor); the Python side
    // keeps the row -> key metadata and folds snapshots into the online
    // cost model at the histogram registry's detach points. -1 = node
    // not attributed (no extra cost for it beyond the row load).
    std::vector<int32_t> *cost_rows;  // per node row id; empty = unbound
    std::atomic<uint64_t> *cost_cnt;  // per row: tasks accumulated
    std::atomic<uint64_t> *cost_sum;  // per row: summed amortized ns
    int32_t n_cost_rows;
    // scheduler plane binding (sched_bind, ISSUE 9): when set, the ready
    // structure lives in the shared multi-pool plane (pool `spool`) — N
    // concurrent lane graphs then share the workers by DRR weight instead
    // of whoever sits at the front of the context's lane queue. The
    // capsule ref keeps the plane alive for the binding window.
    ptsched::Plane *splane;
    int32_t spool;
    PyObject *sched_cap;
};

bool parse_i32_list(PyObject *obj, std::vector<int32_t> &out,
                    const char *what) {
    PyObject *fast = PySequence_Fast(obj, what);
    if (!fast) return false;
    Py_ssize_t k = PySequence_Fast_GET_SIZE(fast);
    out.resize((size_t)k);
    PyObject **items = PySequence_Fast_ITEMS(fast);
    for (Py_ssize_t i = 0; i < k; i++) {
        long v = PyLong_AsLong(items[i]);
        if (v == -1 && PyErr_Occurred()) { Py_DECREF(fast); return false; }
        out[(size_t)i] = (int32_t)v;
    }
    Py_DECREF(fast);
    return true;
}

// max-heap ordering on (priority, id): a pop yields a maximal-priority
// ready task; among equal priorities the higher id wins (deterministic,
// roughly LIFO for sequentially-released work).
struct PrioLess {
    const int32_t *p;
    bool operator()(int32_t a, int32_t b) const {
        return p[a] < p[b] || (p[a] == p[b] && a < b);
    }
};

// mu held. True when any of task `t`'s input slots is mid-rendezvous.
bool slots_pending_locked(Graph *g, int32_t t) {
    if (g->rdv_pending->empty() || g->in_off->empty()) return false;
    const int32_t *ioff = g->in_off->data();
    const int32_t *islot = g->in_slots->data();
    const uint8_t *pend = g->rdv_pending->data();
    for (int32_t k = ioff[t]; k < ioff[t + 1]; k++)
        if (pend[islot[k]]) return true;
    return false;
}

// mu held. Enter the ready structure (heap-aware) unless an input slot's
// rendezvous is still in flight — then park until rdv_land(). With a
// scheduler plane bound the item enters the plane instead (anonymous
// producer: the callers here — ingest, rdv_land, seeding — have no worker
// identity; the run() release sweep pushes batched with its worker id).
// Device-bodied tasks take neither path: they surface straight onto the
// ptdev lane (lock-free submit; mu-held is fine, it never blocks).
void push_ready_locked(Graph *g, int32_t s) {
    if (g->dev_bound && (*g->dev_mask)[(size_t)s]) {
        g->dsend.submit(g->dsend.dev, g->dev_pool, s);
        // dev_tx/dev_done stay ORIGINAL-task denominated: a fused
        // region node surfaces once but counts its whole region
        g->dev_tx.fetch_add(
            g->weighted ? (*g->weight)[(size_t)s] : 1,
            std::memory_order_relaxed);
        return;
    }
    if (g->comm_bound && slots_pending_locked(g, s)) {
        g->parked->push_back(s);
        return;
    }
    if (g->splane) {
        int32_t prio = g->use_heap ? (*g->prio)[(size_t)s] : 0;
        g->splane->push(g->spool, -1, &s, g->use_heap ? &prio : nullptr, 1);
        return;
    }
    g->ready->push_back(s);
    if (g->use_heap)
        std::push_heap(g->ready->begin(), g->ready->end(),
                       PrioLess{g->prio->data()});
}

// fill `prios` with the per-task priorities of `ids` for a plane push
// (heap pools only); returns the array to pass, or null for non-heap.
// Shared by seeding (reset), the bind-time migration, and the release
// sweep so the priority-stamping rule lives in one place.
const int32_t *gather_prios(Graph *g, const std::vector<int32_t> &ids,
                            std::vector<int32_t> &prios) {
    if (!g->use_heap) return nullptr;
    prios.clear();
    prios.reserve(ids.size());
    for (int32_t s : ids) prios.push_back((*g->prio)[(size_t)s]);
    return prios.data();
}

// mu held. Sweep device-bodied ids out of the private ready structure and
// surface them onto the ptdev lane — the hand-off moment of dev_bind (and
// of a reset on a bound graph): seeds landed in `ready` before the lane
// existed. Returns the count surfaced.
int64_t dev_sweep_ready_locked(Graph *g) {
    if (!g->dev_bound || g->ready->empty()) return 0;
    const uint8_t *dmask = g->dev_mask->data();
    int64_t sent = 0;
    size_t w = 0;
    std::vector<int32_t> &rd = *g->ready;
    for (size_t i = 0; i < rd.size(); i++) {
        int32_t s = rd[i];
        if (dmask[s]) {
            g->dsend.submit(g->dsend.dev, g->dev_pool, s);
            sent += g->weighted ? (*g->weight)[(size_t)s] : 1;
        } else {
            rd[w++] = s;
        }
    }
    rd.resize(w);
    if (sent) {
        g->dev_tx.fetch_add(sent, std::memory_order_relaxed);
        if (g->use_heap)
            std::make_heap(rd.begin(), rd.end(), PrioLess{g->prio->data()});
    }
    return sent;
}

// recompute the seed list: with owners bound, only LOCAL zero-goal tasks
// may ever enter the ready structure (remote tasks run on their rank)
void graph_rebuild_seeds(Graph *self) {
    self->seeds->clear();
    self->n_local = 0;
    const bool bound = self->comm_bound;
    for (int64_t i = 0; i < self->n; i++) {
        if (bound && (*self->owners)[(size_t)i] != self->my_rank) continue;
        self->n_local++;
        if ((*self->goals)[(size_t)i] == 0)
            self->seeds->push_back((int32_t)i);
    }
}

void graph_reset_state(Graph *self) {
    for (int64_t i = 0; i < self->n; i++)
        self->counts[i].store((*self->goals)[(size_t)i],
                              std::memory_order_relaxed);
    if (self->splane) {
        // plane-resident ready structure: flush stale items of an
        // abandoned run, then seed the pool afresh (device-bodied seeds
        // surface onto the ptdev lane, never the plane)
        self->splane->pool_clear(self->spool);
        *self->ready = *self->seeds;
        dev_sweep_ready_locked(self);
        if (!self->ready->empty()) {
            std::vector<int32_t> prios;
            self->splane->push(self->spool, -1, self->ready->data(),
                               gather_prios(self, *self->ready, prios),
                               (int)self->ready->size());
        }
        self->ready->clear();
    } else {
        *self->ready = *self->seeds;
        if (self->use_heap)
            std::make_heap(self->ready->begin(), self->ready->end(),
                           PrioLess{self->prio->data()});
        dev_sweep_ready_locked(self);   // device seeds surface to the lane
    }
    std::fill(self->rdv_pending->begin(), self->rdv_pending->end(),
              (uint8_t)0);
    std::fill(self->dev_ret->begin(), self->dev_ret->end(), (uint8_t)0);
    self->parked->clear();
    for (int64_t j = 0; j < self->n_slots; j++)
        self->slot_cnt[j].store((*self->slot_uses)[(size_t)j],
                                std::memory_order_relaxed);
    self->retired->clear();
    self->nb_slots_retired = 0;
    self->completed = 0;
    self->running = 0;
    self->error = false;
    if (self->ready_stamp)
        for (int64_t i = 0; i < self->n; i++)
            self->ready_stamp[i].store(0, std::memory_order_relaxed);
}

PyObject *graph_new(PyTypeObject *type, PyObject *args, PyObject *) {
    PyObject *goals_o, *off_o, *succs_o;
    PyObject *prio_o = Py_None, *in_off_o = Py_None, *in_slots_o = Py_None,
             *uses_o = Py_None;
    if (!PyArg_ParseTuple(args, "OOO|OOOO", &goals_o, &off_o, &succs_o,
                          &prio_o, &in_off_o, &in_slots_o, &uses_o))
        return nullptr;
    Graph *self = reinterpret_cast<Graph *>(type->tp_alloc(type, 0));
    if (!self) return nullptr;
    self->goals = new (std::nothrow) std::vector<int32_t>();
    self->succ_off = new (std::nothrow) std::vector<int32_t>();
    self->succs = new (std::nothrow) std::vector<int32_t>();
    self->seeds = new (std::nothrow) std::vector<int32_t>();
    self->ready = new (std::nothrow) std::vector<int32_t>();
    self->mu = new (std::nothrow) std::mutex();
    self->prio = new (std::nothrow) std::vector<int32_t>();
    self->in_off = new (std::nothrow) std::vector<int32_t>();
    self->in_slots = new (std::nothrow) std::vector<int32_t>();
    self->slot_uses = new (std::nothrow) std::vector<int32_t>();
    self->retired = new (std::nothrow) std::vector<int32_t>();
    self->counts = nullptr;
    self->slot_cnt = nullptr;
    self->use_heap = false;
    self->n_slots = 0;
    new (&self->trace) std::atomic<ptrace_ring::State *>(nullptr);
    new (&self->hist) std::atomic<pthist::State<N_HISTS> *>(nullptr);
    self->ready_stamp = nullptr;
    self->owners = new (std::nothrow) std::vector<int32_t>();
    self->rdv_pending = new (std::nothrow) std::vector<uint8_t>();
    self->parked = new (std::nothrow) std::vector<int32_t>();
    self->my_rank = 0;
    self->pool_id = 0;
    self->comm_bound = false;
    self->send = PtCommSendVtbl{0, nullptr, nullptr};
    self->n_local = 0;
    new (&self->acts_tx) std::atomic<int64_t>(0);
    new (&self->acts_rx) std::atomic<int64_t>(0);
    new (&self->ingest_bad) std::atomic<int64_t>(0);
    self->dev_bound = false;
    self->dev_pool = 0;
    self->dsend = PtDevSubmitVtbl{0, nullptr, nullptr};
    self->dev_mask = new (std::nothrow) std::vector<uint8_t>();
    self->dev_ret = new (std::nothrow) std::vector<uint8_t>();
    new (&self->dev_tx) std::atomic<int64_t>(0);
    new (&self->dev_done) std::atomic<int64_t>(0);
    new (&self->dev_bad) std::atomic<int64_t>(0);
    self->weight = new (std::nothrow) std::vector<int32_t>();
    self->weighted = false;
    self->w_total = 0;
    self->cost_rows = new (std::nothrow) std::vector<int32_t>();
    self->cost_cnt = nullptr;
    self->cost_sum = nullptr;
    self->n_cost_rows = 0;
    self->splane = nullptr;
    self->spool = -1;
    self->sched_cap = nullptr;
    if (!self->goals || !self->succ_off || !self->succs || !self->seeds ||
        !self->ready || !self->mu || !self->prio || !self->in_off ||
        !self->in_slots || !self->slot_uses || !self->retired ||
        !self->owners || !self->rdv_pending || !self->parked ||
        !self->dev_mask || !self->dev_ret || !self->weight ||
        !self->cost_rows) {
        Py_DECREF(self);
        PyErr_NoMemory();
        return nullptr;
    }
    if (!parse_i32_list(goals_o, *self->goals, "goals: sequence of ints") ||
        !parse_i32_list(off_o, *self->succ_off, "succ_off: sequence of ints") ||
        !parse_i32_list(succs_o, *self->succs, "succs: sequence of ints")) {
        Py_DECREF(self);
        return nullptr;
    }
    if (prio_o != Py_None &&
        !parse_i32_list(prio_o, *self->prio, "prio: sequence of ints")) {
        Py_DECREF(self);
        return nullptr;
    }
    if (in_off_o != Py_None) {
        if (in_slots_o == Py_None || uses_o == Py_None) {
            PyErr_SetString(PyExc_TypeError,
                            "in_off requires in_slots and slot_uses");
            Py_DECREF(self);
            return nullptr;
        }
        if (!parse_i32_list(in_off_o, *self->in_off,
                            "in_off: sequence of ints") ||
            !parse_i32_list(in_slots_o, *self->in_slots,
                            "in_slots: sequence of ints") ||
            !parse_i32_list(uses_o, *self->slot_uses,
                            "slot_uses: sequence of ints")) {
            Py_DECREF(self);
            return nullptr;
        }
    }
    self->n = (int64_t)self->goals->size();
    // structural validation once at build: run() then needs no bounds checks
    if ((int64_t)self->succ_off->size() != self->n + 1) {
        PyErr_SetString(PyExc_ValueError, "succ_off must have n+1 entries");
        Py_DECREF(self);
        return nullptr;
    }
    int32_t prev = 0;
    for (int32_t o : *self->succ_off) {
        if (o < prev || (size_t)o > self->succs->size()) {
            PyErr_SetString(PyExc_ValueError, "succ_off not monotone in-range");
            Py_DECREF(self);
            return nullptr;
        }
        prev = o;
    }
    if (!self->succ_off->empty() &&
        (size_t)self->succ_off->back() != self->succs->size()) {
        PyErr_SetString(PyExc_ValueError, "succ_off must end at len(succs)");
        Py_DECREF(self);
        return nullptr;
    }
    for (int32_t s : *self->succs) {
        if (s < 0 || (int64_t)s >= self->n) {
            PyErr_SetString(PyExc_ValueError, "successor id out of range");
            Py_DECREF(self);
            return nullptr;
        }
    }
    if (!self->prio->empty()) {
        if ((int64_t)self->prio->size() != self->n) {
            PyErr_SetString(PyExc_ValueError, "prio must have n entries");
            Py_DECREF(self);
            return nullptr;
        }
        for (int32_t p : *self->prio)
            if (p != 0) { self->use_heap = true; break; }
        if (!self->use_heap) self->prio->clear();   // all-zero: plain stack
    }
    if (!self->in_off->empty()) {
        self->n_slots = (int64_t)self->slot_uses->size();
        if ((int64_t)self->in_off->size() != self->n + 1) {
            PyErr_SetString(PyExc_ValueError, "in_off must have n+1 entries");
            Py_DECREF(self);
            return nullptr;
        }
        prev = 0;
        for (int32_t o : *self->in_off) {
            if (o < prev || (size_t)o > self->in_slots->size()) {
                PyErr_SetString(PyExc_ValueError,
                                "in_off not monotone in-range");
                Py_DECREF(self);
                return nullptr;
            }
            prev = o;
        }
        if ((size_t)self->in_off->back() != self->in_slots->size()) {
            PyErr_SetString(PyExc_ValueError,
                            "in_off must end at len(in_slots)");
            Py_DECREF(self);
            return nullptr;
        }
        for (int32_t j : *self->in_slots) {
            if (j < 0 || (int64_t)j >= self->n_slots) {
                PyErr_SetString(PyExc_ValueError, "input slot id out of range");
                Py_DECREF(self);
                return nullptr;
            }
        }
        for (int32_t u : *self->slot_uses) {
            if (u < 0) {
                PyErr_SetString(PyExc_ValueError, "negative slot usage limit");
                Py_DECREF(self);
                return nullptr;
            }
        }
    }
    for (int64_t i = 0; i < self->n; i++) {
        if ((*self->goals)[(size_t)i] < 0) {
            PyErr_SetString(PyExc_ValueError, "negative goal");
            Py_DECREF(self);
            return nullptr;
        }
    }
    graph_rebuild_seeds(self);
    if (self->n_slots)
        self->rdv_pending->assign((size_t)self->n_slots, 0);
    self->counts = new (std::nothrow) std::atomic<int32_t>[(size_t)self->n];
    if (self->n && !self->counts) {
        Py_DECREF(self);
        PyErr_NoMemory();
        return nullptr;
    }
    self->slot_cnt = new (std::nothrow)
        std::atomic<int32_t>[(size_t)self->n_slots];
    if (self->n_slots && !self->slot_cnt) {
        Py_DECREF(self);
        PyErr_NoMemory();
        return nullptr;
    }
    // allocated at build (8 bytes/task) so hist_enable mid-run never
    // races a GIL-free worker against a growing buffer; written only
    // when histograms are armed
    self->ready_stamp = new (std::nothrow)
        std::atomic<int64_t>[(size_t)self->n];
    if (self->n && !self->ready_stamp) {
        Py_DECREF(self);
        PyErr_NoMemory();
        return nullptr;
    }
    graph_reset_state(self);
    return reinterpret_cast<PyObject *>(self);
}

void graph_dealloc(PyObject *obj) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    if (self->splane) {
        // a graph dying while bound owns its pool slot: free it so the
        // plane never serves stale ids from a dead graph
        self->splane->pool_unregister(self->spool);
        self->splane = nullptr;
    }
    Py_CLEAR(self->sched_cap);
    delete self->goals;
    delete self->succ_off;
    delete self->succs;
    delete self->seeds;
    delete self->ready;
    delete self->mu;
    delete self->prio;
    delete self->in_off;
    delete self->in_slots;
    delete self->slot_uses;
    delete self->retired;
    delete self->owners;
    delete self->rdv_pending;
    delete self->parked;
    delete self->dev_mask;
    delete self->dev_ret;
    delete self->weight;
    delete self->cost_rows;
    delete[] self->cost_cnt;
    delete[] self->cost_sum;
    delete[] self->counts;
    delete[] self->slot_cnt;
    delete[] self->ready_stamp;
    delete self->trace.load(std::memory_order_acquire);
    delete self->hist.load(std::memory_order_acquire);
    Py_TYPE(obj)->tp_free(obj);
}

// reset() — rewind for replay of the same DAG shape (the cached-graph
// reuse that makes a repeated instantiation cost a memcpy, not a rebuild).
// Refused while any worker is mid-run.
PyObject *graph_reset(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        if (self->running > 0) {
            PyErr_SetString(PyExc_RuntimeError,
                            "reset() while workers are running");
            return nullptr;
        }
    }
    graph_reset_state(self);
    Py_RETURN_NONE;
}

// run(callback, batch, budget) -> number of tasks this caller executed.
//
//   callback: None for empty bodies (pure C walk), else a callable taking
//             one list of ready task ids — or, on a data-mode graph, TWO
//             arguments (ready_ids, retired_slot_ids) — it must run every
//             body; the engine releases those tasks' successors only
//             AFTER it returns (so an observer ordering recorded inside
//             bodies always respects every release edge).
//   batch:    max ids per callback call / per release sweep.
//   budget:   return after executing >= budget tasks even if the graph is
//             not finished (0 = run until starved or done). The caller's
//             hot loop interleaves other work and re-enters.
//
// Returns promptly (never blocks) when the ready structure is empty; check
// done() to distinguish "finished" from "starved while peers run".
PyObject *graph_run(PyObject *obj, PyObject *args) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    PyObject *callback = Py_None;
    int batch = 256;
    long long budget = 0;
    int wid = 0;    // worker id — the scheduler plane's hot-queue affinity
    if (!PyArg_ParseTuple(args, "|OiLi", &callback, &batch, &budget, &wid))
        return nullptr;
    if (batch <= 0) batch = 256;
    if (callback != Py_None && !PyCallable_Check(callback)) {
        PyErr_SetString(PyExc_TypeError, "callback must be callable or None");
        return nullptr;
    }
    const bool data_mode = !self->in_off->empty();
    if (data_mode && callback == Py_None && self->n_slots > 0) {
        // slot values live in Python; a data walk without the dispatcher
        // would retire slots nobody ever clears or reads
        PyErr_SetString(PyExc_TypeError,
                        "data-mode graph requires a callback");
        return nullptr;
    }
    const int32_t *off = self->succ_off->data();
    const int32_t *succ = self->succs->data();
    const int32_t *ioff = data_mode ? self->in_off->data() : nullptr;
    const int32_t *islot = data_mode ? self->in_slots->data() : nullptr;
    const PrioLess cmp{self->use_heap ? self->prio->data() : nullptr};
    std::vector<int32_t> local, fresh, freed, fprio;
    local.reserve((size_t)batch);
    // plane-resident ready structure: pops come out of the shared
    // scheduler plane (hot queue -> pool overflow -> steal) instead of
    // the private vector; pushes go back with this worker's identity
    ptsched::Plane *const spl = self->splane;
    int64_t mine = 0;
    // in-lane tracing: claim a per-worker ring for this call's duration
    // (tw.st stays null when tracing is off — one predictable branch per
    // event site; when tracing is on but every ring is claimed, rec()
    // counts the lost events into State::unclaimed so the drop accounting
    // stays honest, see ptrace_ring.h); the destructor releases the claim
    // on every exit path including a raising callback
    ptrace_ring::Writer tw;
    tw.open(self->trace.load(std::memory_order_acquire));
    const bool tr = tw.st != nullptr;
    // latency histograms: one acquire load per run() call; a disabled
    // state degrades to the same null branch as never-enabled
    pthist::State<N_HISTS> *hs = self->hist.load(std::memory_order_acquire);
    if (hs && !hs->enabled.load(std::memory_order_relaxed)) hs = nullptr;
    // cost-model rows: when bound, the exec bump's amortized per-task
    // cost also lands in the per-row accumulators (cost_bind precedes
    // run() on the enqueue path, so no mid-run race on the vector)
    const int32_t *crow =
        self->cost_rows->empty() ? nullptr : self->cost_rows->data();
    int64_t h_t0 = 0;
    PyThreadState *ts = PyEval_SaveThread();   // GIL dropped for the walk
    for (;;) {
        bool stop = false;
        if (spl) {
            local.resize((size_t)batch);
            int got = spl->pop_pool(self->spool, wid, local.data(), batch);
            local.resize((size_t)got);
            if (got == 0) {
                // drain private-vector leftovers: a graph bound to the
                // plane MID-RUN (lazy arming on the second concurrent
                // pool) may have peers with a pre-bind snapshot still
                // pushing releases into the old structure
                std::lock_guard<std::mutex> lk(*self->mu);
                if (!self->error && !self->ready->empty()) {
                    size_t take =
                        std::min((size_t)batch, self->ready->size());
                    if (self->use_heap) {
                        local.clear();
                        for (size_t i = 0; i < take; i++) {
                            std::pop_heap(self->ready->begin(),
                                          self->ready->end(), cmp);
                            local.push_back(self->ready->back());
                            self->ready->pop_back();
                        }
                    } else {
                        local.assign(self->ready->end() - (ptrdiff_t)take,
                                     self->ready->end());
                        self->ready->resize(self->ready->size() - take);
                    }
                    self->running++;
                } else {
                    local.clear();
                    stop = true;   // starved (or done) — caller decides
                }
            } else {
                std::lock_guard<std::mutex> lk(*self->mu);
                if (self->error) {
                    // poisoned while we popped: drop the claim (the graph
                    // never completes once poisoned, ids need no return)
                    local.clear();
                    stop = true;
                } else {
                    self->running++;
                }
            }
        } else {
            std::lock_guard<std::mutex> lk(*self->mu);
            if (self->error || self->ready->empty()) {
                stop = true;   // done, starved, or poisoned — caller decides
            } else {
                size_t take = std::min((size_t)batch, self->ready->size());
                if (self->use_heap) {
                    // priority pops: the batch comes out highest-first
                    for (size_t i = 0; i < take; i++) {
                        std::pop_heap(self->ready->begin(),
                                      self->ready->end(), cmp);
                        local.push_back(self->ready->back());
                        self->ready->pop_back();
                    }
                } else {
                    local.assign(self->ready->end() - (ptrdiff_t)take,
                                 self->ready->end());
                    self->ready->resize(self->ready->size() - take);
                }
                self->running++;
            }
        }
        if (stop) break;
        if (hs || crow) {
            // ready-queue wait (sampled): pop time minus the stamped
            // push time; unstamped ids (armed mid-flight) are skipped.
            // One clock read per batch — reused as the exec-latency
            // start, and (ISSUE 18) as the cost-row batch start: the
            // cost model rides the histogram's clock reads, it never
            // adds its own
            int64_t now = ptrace_ring::now_ns();
            if (hs) {
                for (int32_t t : local) {
                    if (!hist_sampled(t)) continue;
                    int64_t s0 =
                        self->ready_stamp[t].load(std::memory_order_relaxed);
                    if (s0 > 0) hs->h[H_READY].add(now - s0);
                }
            }
            h_t0 = now;
        }
        if (callback != Py_None) {
            PyEval_RestoreThread(ts);
            ts = nullptr;
            if (tr)
                tw.rec(EV_DISPATCH, (int64_t)local.size(),
                       ptrace_ring::FLAG_START);
            PyObject *ids = PyList_New((Py_ssize_t)local.size());
            PyObject *r = nullptr;
            if (ids) {
                for (size_t i = 0; i < local.size(); i++)
                    PyList_SET_ITEM(ids, (Py_ssize_t)i,
                                    PyLong_FromLong(local[i]));
                if (data_mode) {
                    // hand over every slot retired since the last dispatch
                    // (by ANY worker): the consumer bodies that used them
                    // have all returned, so Python may drop the payloads
                    std::vector<int32_t> ret;
                    {
                        std::lock_guard<std::mutex> lk(*self->mu);
                        ret.swap(*self->retired);
                    }
                    PyObject *rl = PyList_New((Py_ssize_t)ret.size());
                    if (rl) {
                        for (size_t i = 0; i < ret.size(); i++)
                            PyList_SET_ITEM(rl, (Py_ssize_t)i,
                                            PyLong_FromLong(ret[i]));
                        r = PyObject_CallFunctionObjArgs(callback, ids, rl,
                                                         nullptr);
                        Py_DECREF(rl);
                    }
                } else {
                    r = PyObject_CallFunctionObjArgs(callback, ids, nullptr);
                }
                Py_DECREF(ids);
                Py_XDECREF(r);
            }
            if (!r) {
                // a body raised: poison the graph so peers stop pulling
                // work, undo our in-flight claim, propagate the exception
                std::lock_guard<std::mutex> lk(*self->mu);
                self->error = true;
                self->running--;
                return nullptr;
            }
            if (tr)
                tw.rec(EV_DISPATCH, (int64_t)local.size(),
                       ptrace_ring::FLAG_END);
            ts = PyEval_SaveThread();
        }
        fresh.clear();
        freed.clear();
        const bool bound = self->comm_bound;
        const int32_t *own = bound ? self->owners->data() : nullptr;
        const bool devb = self->dev_bound;
        const uint8_t *dmask = devb ? self->dev_mask->data() : nullptr;
        int64_t sent = 0, dsent = 0;
        for (int32_t t : local) {
            if (tr) tw.rec(EV_TASK, t, ptrace_ring::FLAG_START);
            for (int32_t k = off[t]; k < off[t + 1]; k++) {
                int32_t s = succ[k];
                if (bound && own[s] != self->my_rank) {
                    // remote successor: the dep-release crosses ranks as
                    // an activation frame — enqueue onto the comm lane's
                    // lock-free send queue, still GIL-free (the funneled
                    // progress thread does the wire work)
                    self->send.send_act(self->send.comm, own[s],
                                        self->pool_id, s);
                    sent++;
                    continue;
                }
                if (self->counts[s].fetch_sub(
                        1, std::memory_order_acq_rel) == 1) {
                    if (devb && dmask[s]) {
                        // device-bodied successor: surfaces onto the
                        // ptdev lane's pending queue instead of the
                        // ready structure — still GIL-free, never blocks
                        self->dsend.submit(self->dsend.dev, self->dev_pool,
                                           s);
                        dsent += self->weighted
                                     ? (*self->weight)[(size_t)s] : 1;
                    } else {
                        fresh.push_back(s);
                    }
                }
            }
            if (data_mode) {
                // the datarepo retire protocol: this task's bodies have
                // run, so each input slot records one completed use; the
                // LAST use retires the slot (usagecnt meets usagelmt)
                for (int32_t k = ioff[t]; k < ioff[t + 1]; k++) {
                    int32_t j = islot[k];
                    if (self->slot_cnt[j].fetch_sub(
                            1, std::memory_order_acq_rel) == 1)
                        freed.push_back(j);
                }
            }
            if (tr) tw.rec(EV_TASK, t, ptrace_ring::FLAG_END);
        }
        if (sent)
            self->acts_tx.fetch_add(sent, std::memory_order_relaxed);
        if (dsent)
            self->dev_tx.fetch_add(dsent, std::memory_order_relaxed);
        if (hs && !fresh.empty()) {
            // stamp sampled newly-ready ids before they enter the ready
            // structure (one clock read per release batch; plain stores)
            int64_t now = ptrace_ring::now_ns();
            for (int32_t s : fresh)
                if (hist_sampled(s))
                    self->ready_stamp[s].store(now,
                                               std::memory_order_relaxed);
        }
        // weighted accounting (region fusion): a fused node retires as
        // `weight` original tasks — completed/mine stay task-denominated
        int64_t batch_w = (int64_t)local.size();
        if (self->weighted) {
            batch_w = 0;
            const int32_t *wts = self->weight->data();
            for (int32_t t : local) batch_w += wts[t];
        }
        // plane-bound graphs push releases AFTER the bookkeeping lock
        // drops (the plane has its own locks; rdv-gated distributed data
        // pools keep the per-item mu-held path, which is plane-aware)
        const bool plane_batch = spl && !(bound && !self->in_off->empty());
        {
            std::lock_guard<std::mutex> lk(*self->mu);
            self->completed += batch_w;
            self->running--;
            if (!fresh.empty() && !plane_batch) {
                if (bound && !self->in_off->empty()) {
                    // distributed data pool: gate on in-flight rendezvous
                    for (int32_t s : fresh) push_ready_locked(self, s);
                } else if (self->use_heap) {
                    for (int32_t s : fresh) {
                        self->ready->push_back(s);
                        std::push_heap(self->ready->begin(),
                                       self->ready->end(), cmp);
                    }
                } else {
                    self->ready->insert(self->ready->end(), fresh.begin(),
                                        fresh.end());
                }
            }
            if (!freed.empty()) {
                self->retired->insert(self->retired->end(), freed.begin(),
                                      freed.end());
                self->nb_slots_retired += (int64_t)freed.size();
            }
        }
        if (plane_batch && !fresh.empty())
            spl->push(self->spool, wid, fresh.data(),
                      gather_prios(self, fresh, fprio),
                      (int)fresh.size());
        if ((hs || crow) && !local.empty()) {
            // per-task execute latency, batch-amortized: the whole
            // dispatch + release sweep cost divided across the batch,
            // bumped once with the batch count — two clock reads and
            // three atomics per ~256 tasks keeps the armed overhead
            // inside the <2% contract. batch_w keeps the denominator
            // ORIGINAL-task denominated on fused pools, like every
            // other counter in this sweep
            int64_t per = (ptrace_ring::now_ns() - h_t0) / batch_w;
            if (hs) hs->h[H_EXEC].add(per, (uint64_t)batch_w);
            if (crow) {
                // cost rows (ISSUE 18): the same amortized cost, split
                // by the compiler's (class, bucket, device) rows — two
                // relaxed atomics per task, no extra clock reads. The
                // weight keeps fused nodes original-task denominated,
                // matching the histogram and w_total accounting.
                const int32_t *wts =
                    self->weighted ? self->weight->data() : nullptr;
                for (int32_t t : local) {
                    int32_t r = crow[t];
                    if (r < 0) continue;
                    uint64_t w = wts ? (uint64_t)wts[t] : 1;
                    self->cost_cnt[r].fetch_add(w,
                                                std::memory_order_relaxed);
                    self->cost_sum[r].fetch_add((uint64_t)per * w,
                                                std::memory_order_relaxed);
                }
            }
        }
        mine += batch_w;
        local.clear();
        if (budget > 0 && mine >= budget) break;
    }
    if (ts) PyEval_RestoreThread(ts);
    return PyLong_FromLongLong(mine);
}

// the completion target: original-task denominated once regions are
// bound (w_total = sum of node weights), node count otherwise
inline int64_t done_target(const Graph *g) {
    return g->weighted ? g->w_total : g->n_local;
}

PyObject *graph_done(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    bool ready_empty =
        self->ready->empty() &&
        (!self->splane || self->splane->queued_of(self->spool) == 0);
    if (!self->error && self->completed == done_target(self) &&
        ready_empty && self->running == 0)
        Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

PyObject *graph_failed(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    if (self->error) Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

PyObject *graph_idle(PyObject *obj, PyObject *) {
    // True when no worker holds a claimed batch. After a poison (error
    // set) no worker can claim a NEW batch, so idle==True is then stable
    // — the safe moment for Python to drop the slot payloads of an
    // abandoned data-mode graph (a mid-callback peer still reads them).
    Graph *self = reinterpret_cast<Graph *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    if (self->running == 0) Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

PyObject *graph_pending(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    return PyLong_FromLongLong(done_target(self) - self->completed);
}

// ------------------------------------------------------- comm lane binding

// The GIL-free entry points the comm progress thread calls through the
// PtCommIngestVtbl capsule (ptcomm_iface.h). Out-of-range ids from the
// wire are counted, never trusted.
void graph_ingest_act_c(void *obj, int32_t tid) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    if (tid < 0 || (int64_t)tid >= self->n ||
        (self->comm_bound &&
         (*self->owners)[(size_t)tid] != self->my_rank)) {
        // in-range but REMOTE-owned ids are just as untrusted as
        // out-of-range ones: decrementing them could locally execute a
        // task this rank does not own and wedge done() accounting
        self->ingest_bad.fetch_add(1, std::memory_order_relaxed);
        return;
    }
    self->acts_rx.fetch_add(1, std::memory_order_relaxed);
    if (self->counts[tid].fetch_sub(1, std::memory_order_acq_rel) == 1) {
        pthist::State<N_HISTS> *hs =
            self->hist.load(std::memory_order_acquire);
        if (hs && hs->enabled.load(std::memory_order_relaxed) &&
            hist_sampled(tid))
            self->ready_stamp[tid].store(ptrace_ring::now_ns(),
                                         std::memory_order_relaxed);
        std::lock_guard<std::mutex> lk(*self->mu);
        push_ready_locked(self, tid);
    }
}

void graph_rdv_begin_c(void *obj, int32_t slot) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    if (slot < 0 || (int64_t)slot >= self->n_slots) {
        self->ingest_bad.fetch_add(1, std::memory_order_relaxed);
        return;
    }
    (*self->rdv_pending)[(size_t)slot] = 1;
}

void graph_rdv_land_c(void *obj, int32_t slot) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    if (slot < 0 || (int64_t)slot >= self->n_slots) {
        self->ingest_bad.fetch_add(1, std::memory_order_relaxed);
        return;
    }
    (*self->rdv_pending)[(size_t)slot] = 0;
    if (self->parked->empty()) return;
    // re-examine parked consumers: any with no remaining in-flight pulls
    // becomes ready (others stay parked for their other slots)
    size_t w = 0;
    std::vector<int32_t> &pk = *self->parked;
    for (size_t i = 0; i < pk.size(); i++) {
        int32_t t = pk[i];
        if (slots_pending_locked(self, t)) {
            pk[w++] = t;
        } else {
            self->ready->push_back(t);
            if (self->use_heap)
                std::push_heap(self->ready->begin(), self->ready->end(),
                               PrioLess{self->prio->data()});
        }
    }
    pk.resize(w);
}

void ingest_capsule_free(PyObject *cap) {
    std::free(PyCapsule_GetPointer(cap, PTCOMM_INGEST_CAPSULE));
}

// ingest_capsule() -> PyCapsule(PtCommIngestVtbl) for Comm.register_pool.
// The capsule borrows `self`: the Python comm lane holds a strong ref to
// the graph for the registration window (ptcomm_iface.h lifetime rules).
PyObject *graph_ingest_capsule(PyObject *obj, PyObject *) {
    PtCommIngestVtbl *v =
        static_cast<PtCommIngestVtbl *>(std::malloc(sizeof(PtCommIngestVtbl)));
    if (!v) return PyErr_NoMemory();
    v->abi = PTCOMM_ABI;
    v->obj = obj;
    v->act = graph_ingest_act_c;
    v->rdv_begin = graph_rdv_begin_c;
    v->rdv_land = graph_rdv_land_c;
    PyObject *cap = PyCapsule_New(v, PTCOMM_INGEST_CAPSULE,
                                  ingest_capsule_free);
    if (!cap) std::free(v);
    return cap;
}

// comm_bind(send_capsule, pool_id, my_rank, owners) — enter distributed
// mode: `owners[i]` names the rank executing task i; local release sweeps
// surface non-local successors through the send vtable. Must be called
// before any run() (the seed list is rebuilt rank-local).
PyObject *graph_comm_bind(PyObject *obj, PyObject *args) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    PyObject *cap, *owners_o;
    unsigned int pool;
    int my_rank;
    if (!PyArg_ParseTuple(args, "OIiO", &cap, &pool, &my_rank, &owners_o))
        return nullptr;
    PtCommSendVtbl *sv = static_cast<PtCommSendVtbl *>(
        PyCapsule_GetPointer(cap, PTCOMM_SEND_CAPSULE));
    if (!sv) return nullptr;
    if (sv->abi != PTCOMM_ABI) {
        PyErr_SetString(PyExc_RuntimeError, "ptcomm ABI mismatch");
        return nullptr;
    }
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        if (self->running > 0 || self->completed > 0) {
            PyErr_SetString(PyExc_RuntimeError,
                            "comm_bind() on a graph already running");
            return nullptr;
        }
        if (self->weighted) {
            PyErr_SetString(PyExc_RuntimeError,
                            "comm_bind() on a region-fused graph (fusion "
                            "is single-rank)");
            return nullptr;
        }
    }
    std::vector<int32_t> owners;
    if (!parse_i32_list(owners_o, owners, "owners: sequence of ints"))
        return nullptr;
    if ((int64_t)owners.size() != self->n) {
        PyErr_SetString(PyExc_ValueError, "owners must have n entries");
        return nullptr;
    }
    *self->owners = std::move(owners);
    self->send = *sv;
    self->pool_id = pool;
    self->my_rank = my_rank;
    self->comm_bound = true;
    if (!self->rdv_pending->size() && self->n_slots)
        self->rdv_pending->assign((size_t)self->n_slots, 0);
    graph_rebuild_seeds(self);
    graph_reset_state(self);
    return Py_BuildValue("L", (long long)self->n_local);
}

// ------------------------------------------------------- device lane bind

// The GIL-free retire entry the ptdev manager thread calls through the
// PtDevRetireVtbl capsule once a dispatched task's completion events
// fired (its outputs already landed in the Python-owned slots): run the
// release walk — successor decrements (more device tasks surface back
// onto the lane; CPU successors enter the ready structure/plane), slot
// retires, completion accounting — exactly the run() sweep, per task.
void graph_dev_retire_c(void *obj, int32_t t) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    if (t < 0 || (int64_t)t >= self->n || !self->dev_bound ||
        !(*self->dev_mask)[(size_t)t]) {
        // ids the lane was never handed are as untrusted as wire ids
        self->dev_bad.fetch_add(1, std::memory_order_relaxed);
        return;
    }
    {
        // duplicate/stale retires (a buggy poll closure, a retire racing
        // a reset) must not double-run the release walk — successor
        // counters would underflow and fire twice or wrap dead
        std::lock_guard<std::mutex> lk(*self->mu);
        if ((*self->dev_ret)[(size_t)t]) {
            self->dev_bad.fetch_add(1, std::memory_order_relaxed);
            return;
        }
        (*self->dev_ret)[(size_t)t] = 1;
    }
    const int32_t *off = self->succ_off->data();
    const int32_t *succ = self->succs->data();
    const bool data_mode = !self->in_off->empty();
    const bool bound = self->comm_bound;
    const int32_t *own = bound ? self->owners->data() : nullptr;
    std::vector<int32_t> fresh, freed;
    for (int32_t k = off[t]; k < off[t + 1]; k++) {
        int32_t s = succ[k];
        if (bound && own[s] != self->my_rank) {
            self->send.send_act(self->send.comm, own[s], self->pool_id, s);
            self->acts_tx.fetch_add(1, std::memory_order_relaxed);
            continue;
        }
        if (self->counts[s].fetch_sub(1, std::memory_order_acq_rel) == 1)
            fresh.push_back(s);
    }
    if (data_mode) {
        const int32_t *ioff = self->in_off->data();
        const int32_t *islot = self->in_slots->data();
        for (int32_t k = ioff[t]; k < ioff[t + 1]; k++) {
            int32_t j = islot[k];
            if (self->slot_cnt[j].fetch_sub(
                    1, std::memory_order_acq_rel) == 1)
                freed.push_back(j);
        }
    }
    pthist::State<N_HISTS> *hs = self->hist.load(std::memory_order_acquire);
    if (hs && hs->enabled.load(std::memory_order_relaxed) &&
        !fresh.empty()) {
        int64_t now = ptrace_ring::now_ns();
        for (int32_t s : fresh)
            if (hist_sampled(s))
                self->ready_stamp[s].store(now, std::memory_order_relaxed);
    }
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        self->completed += self->weighted ? (*self->weight)[(size_t)t] : 1;
        // push_ready_locked routes each successor: device-bodied back to
        // the lane, plane-bound to the plane, the rest to the vector
        for (int32_t s : fresh) push_ready_locked(self, s);
        if (!freed.empty()) {
            self->retired->insert(self->retired->end(), freed.begin(),
                                  freed.end());
            self->nb_slots_retired += (int64_t)freed.size();
        }
    }
    self->dev_done.fetch_add(
        self->weighted ? (*self->weight)[(size_t)t] : 1,
        std::memory_order_relaxed);
    ptrace_ring::Writer tw;
    tw.open(self->trace.load(std::memory_order_acquire));
    if (tw.st) {
        // the device task's retire step as a (tiny) EV_TASK interval so
        // merged traces pair every lane task exactly like CPU retires;
        // fused-region nodes additionally mark EV_REGION so the merged
        // timeline separates regions from seams on the retire side too
        if (self->weighted && (*self->weight)[(size_t)t] > 1) {
            tw.rec(EV_REGION, t, ptrace_ring::FLAG_START);
            tw.rec(EV_REGION, t, ptrace_ring::FLAG_END);
        }
        tw.rec(EV_TASK, t, ptrace_ring::FLAG_START);
        tw.rec(EV_TASK, t, ptrace_ring::FLAG_END);
    }
}

void dev_retire_capsule_free(PyObject *cap) {
    std::free(PyCapsule_GetPointer(cap, PTDEV_RETIRE_CAPSULE));
}

// dev_retire_capsule() -> PyCapsule(PtDevRetireVtbl) for Lane.bind_pool.
// The capsule borrows `self`: the device lane holds a strong ref to the
// graph for the bind window (ptdev_iface.h lifetime rules).
PyObject *graph_dev_retire_capsule(PyObject *obj, PyObject *) {
    PtDevRetireVtbl *v =
        static_cast<PtDevRetireVtbl *>(std::malloc(sizeof(PtDevRetireVtbl)));
    if (!v) return PyErr_NoMemory();
    v->abi = PTDEV_ABI;
    v->obj = obj;
    v->retire = graph_dev_retire_c;
    PyObject *cap = PyCapsule_New(v, PTDEV_RETIRE_CAPSULE,
                                  dev_retire_capsule_free);
    if (!cap) std::free(v);
    return cap;
}

// dev_bind(submit_capsule, dev_pool, mask) -> n_seeded — enter device
// mode: `mask[i]` flags task i as device-bodied. Ready device tasks
// already seeded into the private structure surface onto the lane NOW
// (the hand-off of dev_sweep_ready_locked); everything after routes at
// the release sites. Bind BEFORE the context enqueues the graph (and
// before any sched_bind) so no device id ever reaches the plane.
PyObject *graph_dev_bind(PyObject *obj, PyObject *args) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    PyObject *cap, *mask_o;
    unsigned int pool;
    if (!PyArg_ParseTuple(args, "OIO", &cap, &pool, &mask_o))
        return nullptr;
    PtDevSubmitVtbl *sv = static_cast<PtDevSubmitVtbl *>(
        PyCapsule_GetPointer(cap, PTDEV_SUBMIT_CAPSULE));
    if (!sv) return nullptr;
    if (sv->abi != PTDEV_ABI) {
        PyErr_SetString(PyExc_RuntimeError, "ptdev ABI mismatch");
        return nullptr;
    }
    std::vector<int32_t> mask32;
    if (!parse_i32_list(mask_o, mask32, "mask: sequence of ints"))
        return nullptr;
    if ((int64_t)mask32.size() != self->n) {
        PyErr_SetString(PyExc_ValueError, "mask must have n entries");
        return nullptr;
    }
    int64_t seeded;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        if (self->dev_bound) {
            PyErr_SetString(PyExc_RuntimeError, "graph already dev-bound");
            return nullptr;
        }
        if (self->running > 0 || self->completed > 0) {
            PyErr_SetString(PyExc_RuntimeError,
                            "dev_bind() on a graph already running");
            return nullptr;
        }
        self->dev_mask->resize((size_t)self->n);
        self->dev_ret->assign((size_t)self->n, 0);
        for (int64_t i = 0; i < self->n; i++)
            (*self->dev_mask)[(size_t)i] = mask32[(size_t)i] ? 1 : 0;
        self->dsend = *sv;
        self->dev_pool = pool;
        self->dev_bound = true;
        seeded = dev_sweep_ready_locked(self);
    }
    return PyLong_FromLongLong(seeded);
}

// Python mirror of the C retire entry (tests + non-native drivers)
PyObject *graph_dev_retire(PyObject *obj, PyObject *arg) {
    long tid = PyLong_AsLong(arg);
    if (tid == -1 && PyErr_Occurred()) return nullptr;
    graph_dev_retire_c(obj, (int32_t)tid);
    Py_RETURN_NONE;
}

PyObject *graph_dev_stats(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    int64_t ndev = 0;
    for (size_t i = 0; i < self->dev_mask->size(); i++)
        if ((*self->dev_mask)[i])
            ndev += self->weighted ? (*self->weight)[i] : 1;
    return Py_BuildValue(
        "{s:L,s:L,s:L,s:L}",
        "dev_tx", (long long)self->dev_tx.load(std::memory_order_relaxed),
        "dev_done",
        (long long)self->dev_done.load(std::memory_order_relaxed),
        "dev_bad", (long long)self->dev_bad.load(std::memory_order_relaxed),
        "n_dev", (long long)ndev);
}

// ------------------------------------------------------ region fusion bind

// region_bind(weights) — declare fused super-task nodes (ISSUE 12). The
// compiler's fusion pass already rebuilt the CSR so each fused node
// carries the union of its region's external in/out edges and in-slot
// list; `weights[i]` says how many ORIGINAL tasks node i stands for
// (1 for seams and unfused tasks, the region size for a fused node).
// From here completed/pending/done and run()'s return value are
// original-task denominated, so pool accounting and engagement counters
// never under-report a fused pool. Single-rank only (fusion declines
// distributed pools: a fused region must not hide a cross-rank edge).
PyObject *graph_region_bind(PyObject *obj, PyObject *arg) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    std::vector<int32_t> w;
    if (!parse_i32_list(arg, w, "weights: sequence of ints"))
        return nullptr;
    if ((int64_t)w.size() != self->n) {
        PyErr_SetString(PyExc_ValueError, "weights must have n entries");
        return nullptr;
    }
    int64_t total = 0;
    for (int32_t v : w) {
        if (v < 1) {
            PyErr_SetString(PyExc_ValueError, "region weight must be >= 1");
            return nullptr;
        }
        total += v;
    }
    std::lock_guard<std::mutex> lk(*self->mu);
    if (self->running > 0 || self->completed > 0) {
        PyErr_SetString(PyExc_RuntimeError,
                        "region_bind() on a graph already running");
        return nullptr;
    }
    if (self->comm_bound) {
        PyErr_SetString(PyExc_RuntimeError,
                        "region_bind() on a comm-bound graph (fusion is "
                        "single-rank)");
        return nullptr;
    }
    *self->weight = std::move(w);
    self->w_total = total;
    self->weighted = true;
    return Py_BuildValue("L", (long long)total);
}

PyObject *graph_region_stats(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    int64_t regions = 0, fused = 0;
    for (int32_t v : *self->weight) {
        if (v > 1) {
            regions++;
            fused += v;
        }
    }
    return Py_BuildValue(
        "{s:L,s:L,s:L,s:L}",
        "fused_regions", (long long)regions,
        "fused_tasks", (long long)fused,
        "nodes", (long long)self->n,
        "weighted_total", (long long)(self->weighted ? self->w_total
                                                     : self->n_local));
}

// cost_bind(rows) — attach cost-model rows (ISSUE 18): rows[i] is the
// accumulator row task i reports into (-1 = unattributed). The compiler
// assigns one row per (class, shape bucket, device flavor) and keeps the
// row -> key metadata Python-side; run()'s exec bump then splits its
// batch-amortized per-task cost across the rows at two relaxed atomics
// per task. Bind before enqueue (the lane does) — run() snapshots the
// row pointer once per call.
PyObject *graph_cost_bind(PyObject *obj, PyObject *arg) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    std::vector<int32_t> rows;
    if (!parse_i32_list(arg, rows, "rows: sequence of ints"))
        return nullptr;
    if ((int64_t)rows.size() != self->n) {
        PyErr_SetString(PyExc_ValueError, "rows must have n entries");
        return nullptr;
    }
    int32_t nrows = 0;
    for (int32_t r : rows) {
        if (r < -1) {
            PyErr_SetString(PyExc_ValueError, "row ids must be >= -1");
            return nullptr;
        }
        if (r >= nrows) nrows = r + 1;
    }
    std::lock_guard<std::mutex> lk(*self->mu);
    if (self->running > 0) {
        PyErr_SetString(PyExc_RuntimeError,
                        "cost_bind() on a graph already running");
        return nullptr;
    }
    delete[] self->cost_cnt;
    delete[] self->cost_sum;
    self->cost_cnt = nullptr;
    self->cost_sum = nullptr;
    if (nrows > 0) {
        self->cost_cnt = new (std::nothrow) std::atomic<uint64_t>[nrows];
        self->cost_sum = new (std::nothrow) std::atomic<uint64_t>[nrows];
        if (!self->cost_cnt || !self->cost_sum) {
            delete[] self->cost_cnt;
            delete[] self->cost_sum;
            self->cost_cnt = nullptr;
            self->cost_sum = nullptr;
            PyErr_NoMemory();
            return nullptr;
        }
        for (int32_t r = 0; r < nrows; r++) {
            self->cost_cnt[r].store(0, std::memory_order_relaxed);
            self->cost_sum[r].store(0, std::memory_order_relaxed);
        }
    }
    *self->cost_rows = std::move(rows);
    self->n_cost_rows = nrows;
    return PyLong_FromLong((long)nrows);
}

// cost_snapshot() -> [(count, sum_ns)] per row — drained by the Python
// fold at the histogram registry's detach points. Relaxed reads: a
// concurrent bump may straddle the snapshot, but folds only run once
// the lane's graph is done (or abandoned), so the pairs are settled.
PyObject *graph_cost_snapshot(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    PyObject *out = PyList_New((Py_ssize_t)self->n_cost_rows);
    if (!out) return nullptr;
    for (int32_t r = 0; r < self->n_cost_rows; r++) {
        PyObject *pair = Py_BuildValue(
            "(KK)",
            (unsigned long long)self->cost_cnt[r].load(
                std::memory_order_relaxed),
            (unsigned long long)self->cost_sum[r].load(
                std::memory_order_relaxed));
        if (!pair) {
            Py_DECREF(out);
            return nullptr;
        }
        PyList_SET_ITEM(out, (Py_ssize_t)r, pair);
    }
    return out;
}

// trace_mark(key, id, flags) — record one event into this graph's rings
// from Python (GIL held). The region dispatch wrappers bracket each
// fused-region body with EV_REGION START/END so merged Perfetto
// timelines show regions vs seams; a disarmed tracer costs one null
// branch (Writer.open on a null state).
PyObject *graph_trace_mark(PyObject *obj, PyObject *args) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    unsigned int key, flags;
    long long id;
    if (!PyArg_ParseTuple(args, "ILI", &key, &id, &flags))
        return nullptr;
    ptrace_ring::Writer tw;
    tw.open(self->trace.load(std::memory_order_acquire));
    if (tw.st) tw.rec(key, (int64_t)id, flags);
    Py_RETURN_NONE;
}

// --------------------------------------------------- scheduler plane bind

// sched_bind(plane_capsule, pool_handle) — move this graph's ready
// structure into the shared scheduler plane (ISSUE 9): pushes enter the
// plane (per-worker hot queues / per-pool heaps), pops come back through
// run()'s plane path, and the Context arbitrates ACROSS bound graphs by
// DRR weight. Items already ready (seeds, a reset graph) migrate now.
// The graph owns the pool slot: sched_unbind()/dealloc frees it.
PyObject *graph_sched_bind(PyObject *obj, PyObject *args) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    PyObject *cap;
    int h;
    if (!PyArg_ParseTuple(args, "Oi", &cap, &h))
        return nullptr;
    ptsched::Plane *pl = ptsched::plane_from_capsule(cap);
    if (!pl) return nullptr;
    if (h < 0 || h >= ptsched::MAX_POOLS) {
        PyErr_SetString(PyExc_IndexError, "bad pool handle");
        return nullptr;
    }
    std::lock_guard<std::mutex> lk(*self->mu);
    if (self->splane) {
        PyErr_SetString(PyExc_RuntimeError, "graph already sched-bound");
        return nullptr;
    }
    // binding MID-RUN is legal (lazy arming on the second concurrent
    // pool): the ready vector migrates under mu here; a worker holding a
    // pre-bind snapshot keeps pushing/popping the private vector, whose
    // leftovers plane-era pops drain under the same mu — nothing is lost
    // or duplicated, only the heap ordering mixes transiently
    Py_INCREF(cap);
    self->sched_cap = cap;
    self->splane = pl;
    self->spool = h;
    if (self->use_heap) {
        // a priority graph's plane pool must keep heap order from the
        // first push — per-batch all-zero priorities must not slip into
        // the FIFO-ish hot queues ahead of heaped higher priorities
        std::lock_guard<std::mutex> pm(pl->pools[h].mu);
        pl->pools[h].heap = true;
    }
    if (!self->ready->empty()) {
        std::vector<int32_t> prios;
        pl->push(h, -1, self->ready->data(),
                 gather_prios(self, *self->ready, prios),
                 (int)self->ready->size());
        self->ready->clear();
    }
    Py_RETURN_NONE;
}

// sched_unbind() — leave the plane: straggler items are swept, the pool
// slot freed, the capsule ref dropped. Any already-ready items migrate
// back into the private vector first (an errored/finished graph has
// none that matter; a live rebind path must not lose work).
PyObject *graph_sched_unbind(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    if (!self->splane) Py_RETURN_NONE;
    if (self->running > 0) {
        // a mid-batch worker's release sweep would push into a freed
        // (possibly reused) pool slot; callers unbind at idle points
        // (finalize, abandon-after-poison)
        PyErr_SetString(PyExc_RuntimeError,
                        "sched_unbind() while workers are running");
        return nullptr;
    }
    ptsched::Plane *pl = self->splane;
    int h = self->spool;
    // migrate EVERY queued item back into the private structure before
    // the slot frees (pool_drain_all takes blocking locks — the regular
    // pop's try_lock steal would skip a contended victim's hot queue and
    // the unregister sweep would then silently drop its items)
    std::vector<int32_t> left;
    pl->pool_drain_all(h, left);
    for (int32_t t : left) {
        self->ready->push_back(t);
        if (self->use_heap)
            std::push_heap(self->ready->begin(), self->ready->end(),
                           PrioLess{self->prio->data()});
    }
    pl->pool_unregister(h);
    self->splane = nullptr;
    self->spool = -1;
    Py_CLEAR(self->sched_cap);
    Py_RETURN_NONE;
}

PyObject *graph_sched_stats(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    if (!self->splane) Py_RETURN_NONE;
    ptsched::Pool &p = self->splane->pools[self->spool];
    return Py_BuildValue(
        "{s:i,s:L,s:L,s:L,s:L}",
        "pool", (int)self->spool,
        "queued", (long long)p.queued.load(std::memory_order_relaxed),
        "served", (long long)p.served.load(std::memory_order_relaxed),
        "spills", (long long)p.spills.load(std::memory_order_relaxed),
        "inflight", (long long)p.inflight.load(std::memory_order_relaxed));
}

// Python-side mirrors of the C ingest entries (tests + non-native drivers)
PyObject *graph_ingest(PyObject *obj, PyObject *arg) {
    long tid = PyLong_AsLong(arg);
    if (tid == -1 && PyErr_Occurred()) return nullptr;
    graph_ingest_act_c(obj, (int32_t)tid);
    Py_RETURN_NONE;
}

PyObject *graph_rdv_begin(PyObject *obj, PyObject *arg) {
    long slot = PyLong_AsLong(arg);
    if (slot == -1 && PyErr_Occurred()) return nullptr;
    graph_rdv_begin_c(obj, (int32_t)slot);
    Py_RETURN_NONE;
}

PyObject *graph_rdv_land(PyObject *obj, PyObject *arg) {
    long slot = PyLong_AsLong(arg);
    if (slot == -1 && PyErr_Occurred()) return nullptr;
    graph_rdv_land_c(obj, (int32_t)slot);
    Py_RETURN_NONE;
}

PyObject *graph_comm_stats(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    int64_t parked;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        parked = (int64_t)self->parked->size();
    }
    return Py_BuildValue(
        "{s:L,s:L,s:L,s:L,s:L}",
        "acts_tx", (long long)self->acts_tx.load(std::memory_order_relaxed),
        "acts_rx", (long long)self->acts_rx.load(std::memory_order_relaxed),
        "ingest_bad",
        (long long)self->ingest_bad.load(std::memory_order_relaxed),
        "n_local", (long long)self->n_local, "parked", (long long)parked);
}

PyObject *graph_size(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    return Py_BuildValue("(Ln)", (long long)self->n,
                         (Py_ssize_t)self->succs->size());
}

PyObject *graph_slot_stats(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    return Py_BuildValue("(LL)", (long long)self->n_slots,
                         (long long)self->nb_slots_retired);
}

// ------------------------------------------------------- in-lane tracing

PyObject *graph_trace_enable(PyObject *obj, PyObject *args) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    return ptrace_ring::py_trace_enable(self->trace, args);
}

PyObject *graph_trace_disable(PyObject *obj, PyObject *) {
    return ptrace_ring::py_trace_disable(
        reinterpret_cast<Graph *>(obj)->trace.load(
            std::memory_order_acquire));
}

PyObject *graph_trace_drain(PyObject *obj, PyObject *) {
    return ptrace_ring::py_trace_drain(reinterpret_cast<Graph *>(obj)->trace.load(
            std::memory_order_acquire));
}

PyObject *graph_trace_dropped(PyObject *obj, PyObject *) {
    return ptrace_ring::py_trace_dropped(
        reinterpret_cast<Graph *>(obj)->trace.load(
            std::memory_order_acquire));
}

PyObject *graph_monotonic_ns(PyObject *, PyObject *) {
    return PyLong_FromLongLong(ptrace_ring::now_ns());
}

// --------------------------------------------------- latency histograms

PyObject *graph_hist_enable(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    PyObject *r = pthist::py_hist_enable<N_HISTS>(self->hist);
    if (!r) return nullptr;
    // stamp sampled tasks ALREADY awaiting pop (seeds, mid-run arming)
    // so their eventual pop reads a real push time, not zero
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        int64_t now = ptrace_ring::now_ns();
        for (int32_t t : *self->ready)
            if (hist_sampled(t))
                self->ready_stamp[t].store(now, std::memory_order_relaxed);
        for (int32_t t : *self->parked)
            if (hist_sampled(t))
                self->ready_stamp[t].store(now, std::memory_order_relaxed);
    }
    return r;
}

PyObject *graph_hist_disable(PyObject *obj, PyObject *) {
    return pthist::py_hist_disable<N_HISTS>(
        reinterpret_cast<Graph *>(obj)->hist.load(
            std::memory_order_acquire));
}

PyObject *graph_hist_snapshot(PyObject *obj, PyObject *) {
    return pthist::py_hist_snapshot<N_HISTS>(
        reinterpret_cast<Graph *>(obj)->hist.load(
            std::memory_order_acquire),
        HIST_NAMES);
}

PyMethodDef graph_methods[] = {
    {"run", graph_run, METH_VARARGS,
     "run(callback=None, batch=256, budget=0, wid=0) -> tasks executed by "
     "this call (wid = scheduler-plane hot-queue affinity when bound)"},
    {"sched_bind", graph_sched_bind, METH_VARARGS,
     "sched_bind(plane_capsule, pool_handle): move the ready structure "
     "into the shared scheduler plane (see native/src/ptsched.h)"},
    {"sched_unbind", graph_sched_unbind, METH_NOARGS,
     "leave the scheduler plane (frees the pool slot; queued items "
     "migrate back to the private ready structure)"},
    {"sched_stats", graph_sched_stats, METH_NOARGS,
     "{pool, queued, served, spills, inflight} of the bound plane pool, "
     "or None when unbound"},
    {"reset", graph_reset, METH_NOARGS,
     "rewind dependency counters, slots, and the ready structure for replay"},
    {"done", graph_done, METH_NOARGS,
     "True when every task executed (and no error poisoned the run)"},
    {"failed", graph_failed, METH_NOARGS,
     "True when a body callback raised and poisoned the run"},
    {"idle", graph_idle, METH_NOARGS,
     "True when no worker holds a claimed batch (stable once poisoned)"},
    {"pending", graph_pending, METH_NOARGS,
     "tasks not yet executed"},
    {"size", graph_size, METH_NOARGS,
     "(n_tasks, n_edges)"},
    {"slot_stats", graph_slot_stats, METH_NOARGS,
     "(n_slots, n_slots_retired) — the lane-side datarepo retire counters"},
    {"comm_bind", graph_comm_bind, METH_VARARGS,
     "comm_bind(send_capsule, pool_id, my_rank, owners) -> n_local: enter "
     "distributed mode (remote successors surface on the comm lane)"},
    {"ingest_capsule", graph_ingest_capsule, METH_NOARGS,
     "PyCapsule(PtCommIngestVtbl) for Comm.register_pool (GIL-free ingest)"},
    {"ingest", graph_ingest, METH_O,
     "ingest(tid): one remote dep-release arrived for task tid"},
    {"rdv_begin", graph_rdv_begin, METH_O,
     "rdv_begin(slot): gate consumers of slot until its pull lands"},
    {"rdv_land", graph_rdv_land, METH_O,
     "rdv_land(slot): pull landed; release parked consumers"},
    {"comm_stats", graph_comm_stats, METH_NOARGS,
     "{acts_tx, acts_rx, ingest_bad, n_local, parked}"},
    {"dev_bind", graph_dev_bind, METH_VARARGS,
     "dev_bind(submit_capsule, dev_pool, mask) -> n_seeded: enter device "
     "mode (masked tasks surface onto the ptdev lane when ready)"},
    {"dev_retire_capsule", graph_dev_retire_capsule, METH_NOARGS,
     "PyCapsule(PtDevRetireVtbl) for Lane.bind_pool (GIL-free retirement)"},
    {"dev_retire", graph_dev_retire, METH_O,
     "dev_retire(tid): one device task completed; run its release walk"},
    {"dev_stats", graph_dev_stats, METH_NOARGS,
     "{dev_tx, dev_done, dev_bad, n_dev}"},
    {"region_bind", graph_region_bind, METH_O,
     "region_bind(weights) -> weighted total: declare fused super-task "
     "nodes (weight = original tasks per node); completed/pending/done "
     "and run() become original-task denominated"},
    {"region_stats", graph_region_stats, METH_NOARGS,
     "{fused_regions, fused_tasks, nodes, weighted_total}"},
    {"cost_bind", graph_cost_bind, METH_O,
     "cost_bind(rows) -> n_rows: attach per-(class, bucket, device) "
     "cost-model rows (-1 = unattributed); run()'s batch-amortized exec "
     "bump splits its cost across the rows (ISSUE 18)"},
    {"cost_snapshot", graph_cost_snapshot, METH_NOARGS,
     "cost_snapshot() -> [(count, sum_ns)] per row — folded into the "
     "online cost model at lane detach"},
    {"trace_mark", graph_trace_mark, METH_VARARGS,
     "trace_mark(key, id, flags): record one ring event from Python "
     "(EV_REGION dispatch intervals of the fused-region wrappers)"},
    {"trace_enable", graph_trace_enable, METH_VARARGS,
     "trace_enable(nrings=16, capacity=65536) -> (nrings, cap): arm the "
     "in-lane event rings (idempotent; see ptrace_ring.h)"},
    {"trace_disable", graph_trace_disable, METH_NOARGS,
     "stop recording (rings and drop counters are kept)"},
    {"trace_drain", graph_trace_drain, METH_NOARGS,
     "trace_drain() -> [(ring_id, packed_events_bytes)]; event layout "
     "'<qqII' = (t_ns, id, key, flags)"},
    {"trace_dropped", graph_trace_dropped, METH_NOARGS,
     "cumulative events lost to ring overflow (never reset)"},
    {"monotonic_ns", graph_monotonic_ns, METH_NOARGS,
     "the trace clock (steady_clock ns) — for epoch calibration"},
    {"hist_enable", graph_hist_enable, METH_NOARGS,
     "arm the in-lane latency histograms (exec_ns batch-amortized, "
     "ready_wait_ns sampled 1-in-8 by task id; see pthist.h)"},
    {"hist_disable", graph_hist_disable, METH_NOARGS,
     "stop recording (buckets are kept)"},
    {"hist_snapshot", graph_hist_snapshot, METH_NOARGS,
     "{name: (count, sum_ns, buckets_bytes)} — buckets pack '<496Q'"},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject GraphType = [] {
    PyTypeObject t = {PyVarObject_HEAD_INIT(nullptr, 0)};
    t.tp_name = "parsec_tpu._ptexec.Graph";
    t.tp_basicsize = sizeof(Graph);
    t.tp_flags = Py_TPFLAGS_DEFAULT;
    t.tp_doc = "flattened task graph executed by the native FSM lane";
    t.tp_new = graph_new;
    t.tp_dealloc = graph_dealloc;
    t.tp_methods = graph_methods;
    return t;
}();

PyModuleDef ptexec_module = {
    PyModuleDef_HEAD_INIT, "_ptexec",
    "native PTG execution lane (see native/src/ptexec.cpp)", -1,
    nullptr, nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__ptexec(void) {
    if (PyType_Ready(&GraphType) < 0) return nullptr;
    PyObject *m = PyModule_Create(&ptexec_module);
    if (!m) return nullptr;
    Py_INCREF(&GraphType);
    if (PyModule_AddObject(m, "Graph",
                           reinterpret_cast<PyObject *>(&GraphType)) < 0) {
        Py_DECREF(&GraphType);
        Py_DECREF(m);
        return nullptr;
    }
    if (PyModule_AddIntConstant(m, "EV_TASK", EV_TASK) < 0 ||
        PyModule_AddIntConstant(m, "EV_DISPATCH", EV_DISPATCH) < 0 ||
        PyModule_AddIntConstant(m, "EV_REGION", EV_REGION) < 0 ||
        PyModule_AddIntConstant(m, "FLAG_START",
                                ptrace_ring::FLAG_START) < 0 ||
        PyModule_AddIntConstant(m, "FLAG_END", ptrace_ring::FLAG_END) < 0 ||
        PyModule_AddIntConstant(m, "HIST_BUCKETS", pthist::NBUCKETS) < 0 ||
        PyModule_AddIntConstant(m, "HIST_SUB_BITS", pthist::SUB_BITS) < 0 ||
        PyModule_AddIntConstant(m, "HIST_READY_SAMPLE", 8) < 0) {
        Py_DECREF(m);
        return nullptr;
    }
    return m;
}
