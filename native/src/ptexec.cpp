// parsec_tpu._ptexec — the generic task FSM as a CPython extension.
//
// Stands where the reference's generated-C PTG execute path stands
// (the task FSM of parsec/scheduling.c:507-569 driven by generated
// release_deps/iterate_successors, parsec/parsec.c:1837): dependency-count
// decrement, ready-detect, dispatch, and successor release run inside ONE
// C call per *batch* of tasks. The lesson applied here is the same one the
// TPU ahead-of-time compilation line of work draws (arXiv:1810.09868):
// lowering the whole CONTROL STRUCTURE out of the interpreted host
// language — not just the task bodies — is where the order of magnitude
// lives. The Python side (dsl/ptg/compiler.py) plays jdf2c: it flattens a
// PTG taskpool's dependency structure into the CSR successor table this
// engine consumes, once per (program, globals) shape.
//
// Concurrency contract: run() may be called from MANY Python threads on
// the same Graph. The GIL is dropped for the whole FSM walk (ready-pop,
// decrement, release) and re-acquired only to dispatch a batch of
// non-empty task bodies through the Python callback — so for empty/CTL
// task classes the walk is GIL-free end to end and Context(nb_cores>1)
// in-process workers scale on real cores. Shared state is a small mutex
// around the ready stack plus per-task atomic dependency counters; the
// release decrement uses fetch_sub so two workers releasing into the same
// successor can never double-ready it.
//
// run() never blocks waiting for work: a starved worker returns to the
// Python hot loop (which has its own backoff and other task sources) and
// comes back — the "burst handoff into/out of the lane".

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <mutex>
#include <new>
#include <vector>

namespace {

struct Graph {
    PyObject_HEAD
    int64_t n;
    std::vector<int32_t> *goals;     // initial dep count per task
    std::vector<int32_t> *succ_off;  // CSR offsets, n+1 entries
    std::vector<int32_t> *succs;     // flattened successor ids
    std::vector<int32_t> *seeds;     // ids with goal 0
    std::atomic<int32_t> *counts;    // remaining deps per task
    std::mutex *mu;                  // guards ready/completed/running/error
    std::vector<int32_t> *ready;     // LIFO work stack
    int64_t completed;
    int32_t running;                 // workers mid-batch
    bool error;                      // a callback raised somewhere
};

bool parse_i32_list(PyObject *obj, std::vector<int32_t> &out,
                    const char *what) {
    PyObject *fast = PySequence_Fast(obj, what);
    if (!fast) return false;
    Py_ssize_t k = PySequence_Fast_GET_SIZE(fast);
    out.resize((size_t)k);
    PyObject **items = PySequence_Fast_ITEMS(fast);
    for (Py_ssize_t i = 0; i < k; i++) {
        long v = PyLong_AsLong(items[i]);
        if (v == -1 && PyErr_Occurred()) { Py_DECREF(fast); return false; }
        out[(size_t)i] = (int32_t)v;
    }
    Py_DECREF(fast);
    return true;
}

void graph_reset_state(Graph *self) {
    for (int64_t i = 0; i < self->n; i++)
        self->counts[i].store((*self->goals)[(size_t)i],
                              std::memory_order_relaxed);
    *self->ready = *self->seeds;
    self->completed = 0;
    self->running = 0;
    self->error = false;
}

PyObject *graph_new(PyTypeObject *type, PyObject *args, PyObject *) {
    PyObject *goals_o, *off_o, *succs_o;
    if (!PyArg_ParseTuple(args, "OOO", &goals_o, &off_o, &succs_o))
        return nullptr;
    Graph *self = reinterpret_cast<Graph *>(type->tp_alloc(type, 0));
    if (!self) return nullptr;
    self->goals = new (std::nothrow) std::vector<int32_t>();
    self->succ_off = new (std::nothrow) std::vector<int32_t>();
    self->succs = new (std::nothrow) std::vector<int32_t>();
    self->seeds = new (std::nothrow) std::vector<int32_t>();
    self->ready = new (std::nothrow) std::vector<int32_t>();
    self->mu = new (std::nothrow) std::mutex();
    self->counts = nullptr;
    if (!self->goals || !self->succ_off || !self->succs || !self->seeds ||
        !self->ready || !self->mu) {
        Py_DECREF(self);
        PyErr_NoMemory();
        return nullptr;
    }
    if (!parse_i32_list(goals_o, *self->goals, "goals: sequence of ints") ||
        !parse_i32_list(off_o, *self->succ_off, "succ_off: sequence of ints") ||
        !parse_i32_list(succs_o, *self->succs, "succs: sequence of ints")) {
        Py_DECREF(self);
        return nullptr;
    }
    self->n = (int64_t)self->goals->size();
    // structural validation once at build: run() then needs no bounds checks
    if ((int64_t)self->succ_off->size() != self->n + 1) {
        PyErr_SetString(PyExc_ValueError, "succ_off must have n+1 entries");
        Py_DECREF(self);
        return nullptr;
    }
    int32_t prev = 0;
    for (int32_t o : *self->succ_off) {
        if (o < prev || (size_t)o > self->succs->size()) {
            PyErr_SetString(PyExc_ValueError, "succ_off not monotone in-range");
            Py_DECREF(self);
            return nullptr;
        }
        prev = o;
    }
    if (!self->succ_off->empty() &&
        (size_t)self->succ_off->back() != self->succs->size()) {
        PyErr_SetString(PyExc_ValueError, "succ_off must end at len(succs)");
        Py_DECREF(self);
        return nullptr;
    }
    for (int32_t s : *self->succs) {
        if (s < 0 || (int64_t)s >= self->n) {
            PyErr_SetString(PyExc_ValueError, "successor id out of range");
            Py_DECREF(self);
            return nullptr;
        }
    }
    for (int64_t i = 0; i < self->n; i++) {
        int32_t g = (*self->goals)[(size_t)i];
        if (g < 0) {
            PyErr_SetString(PyExc_ValueError, "negative goal");
            Py_DECREF(self);
            return nullptr;
        }
        if (g == 0) self->seeds->push_back((int32_t)i);
    }
    self->counts = new (std::nothrow) std::atomic<int32_t>[(size_t)self->n];
    if (self->n && !self->counts) {
        Py_DECREF(self);
        PyErr_NoMemory();
        return nullptr;
    }
    graph_reset_state(self);
    return reinterpret_cast<PyObject *>(self);
}

void graph_dealloc(PyObject *obj) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    delete self->goals;
    delete self->succ_off;
    delete self->succs;
    delete self->seeds;
    delete self->ready;
    delete self->mu;
    delete[] self->counts;
    Py_TYPE(obj)->tp_free(obj);
}

// reset() — rewind for replay of the same DAG shape (the cached-graph
// reuse that makes a repeated instantiation cost a memcpy, not a rebuild).
// Refused while any worker is mid-run.
PyObject *graph_reset(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        if (self->running > 0) {
            PyErr_SetString(PyExc_RuntimeError,
                            "reset() while workers are running");
            return nullptr;
        }
    }
    graph_reset_state(self);
    Py_RETURN_NONE;
}

// run(callback, batch, budget) -> number of tasks this caller executed.
//
//   callback: None for empty bodies (pure C walk), else a callable taking
//             one list of ready task ids — it must run every body; the
//             engine releases those tasks' successors only AFTER it
//             returns (so an observer ordering recorded inside bodies
//             always respects every release edge).
//   batch:    max ids per callback call / per release sweep.
//   budget:   return after executing >= budget tasks even if the graph is
//             not finished (0 = run until starved or done). The caller's
//             hot loop interleaves other work and re-enters.
//
// Returns promptly (never blocks) when the ready stack is empty; check
// done() to distinguish "finished" from "starved while peers run".
PyObject *graph_run(PyObject *obj, PyObject *args) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    PyObject *callback = Py_None;
    int batch = 256;
    long long budget = 0;
    if (!PyArg_ParseTuple(args, "|OiL", &callback, &batch, &budget))
        return nullptr;
    if (batch <= 0) batch = 256;
    if (callback != Py_None && !PyCallable_Check(callback)) {
        PyErr_SetString(PyExc_TypeError, "callback must be callable or None");
        return nullptr;
    }
    const int32_t *off = self->succ_off->data();
    const int32_t *succ = self->succs->data();
    std::vector<int32_t> local, fresh;
    local.reserve((size_t)batch);
    int64_t mine = 0;
    PyThreadState *ts = PyEval_SaveThread();   // GIL dropped for the walk
    for (;;) {
        bool stop = false;
        {
            std::lock_guard<std::mutex> lk(*self->mu);
            if (self->error || self->ready->empty()) {
                stop = true;   // done, starved, or poisoned — caller decides
            } else {
                size_t take = std::min((size_t)batch, self->ready->size());
                local.assign(self->ready->end() - (ptrdiff_t)take,
                             self->ready->end());
                self->ready->resize(self->ready->size() - take);
                self->running++;
            }
        }
        if (stop) break;
        if (callback != Py_None) {
            PyEval_RestoreThread(ts);
            ts = nullptr;
            PyObject *ids = PyList_New((Py_ssize_t)local.size());
            if (ids) {
                for (size_t i = 0; i < local.size(); i++)
                    PyList_SET_ITEM(ids, (Py_ssize_t)i,
                                    PyLong_FromLong(local[i]));
                PyObject *r = PyObject_CallFunctionObjArgs(callback, ids,
                                                           nullptr);
                Py_DECREF(ids);
                Py_XDECREF(r);
                if (!r) ids = nullptr;   // reuse as the error marker
            }
            if (!ids) {
                // a body raised: poison the graph so peers stop pulling
                // work, undo our in-flight claim, propagate the exception
                std::lock_guard<std::mutex> lk(*self->mu);
                self->error = true;
                self->running--;
                return nullptr;
            }
            ts = PyEval_SaveThread();
        }
        fresh.clear();
        for (int32_t t : local) {
            for (int32_t k = off[t]; k < off[t + 1]; k++) {
                int32_t s = succ[k];
                if (self->counts[s].fetch_sub(
                        1, std::memory_order_acq_rel) == 1)
                    fresh.push_back(s);
            }
        }
        {
            std::lock_guard<std::mutex> lk(*self->mu);
            self->completed += (int64_t)local.size();
            self->running--;
            if (!fresh.empty())
                self->ready->insert(self->ready->end(), fresh.begin(),
                                    fresh.end());
        }
        mine += (int64_t)local.size();
        local.clear();
        if (budget > 0 && mine >= budget) break;
    }
    if (ts) PyEval_RestoreThread(ts);
    return PyLong_FromLongLong(mine);
}

PyObject *graph_done(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    if (!self->error && self->completed == self->n &&
        self->ready->empty() && self->running == 0)
        Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

PyObject *graph_failed(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    if (self->error) Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

PyObject *graph_pending(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    return PyLong_FromLongLong(self->n - self->completed);
}

PyObject *graph_size(PyObject *obj, PyObject *) {
    Graph *self = reinterpret_cast<Graph *>(obj);
    return Py_BuildValue("(Ln)", (long long)self->n,
                         (Py_ssize_t)self->succs->size());
}

PyMethodDef graph_methods[] = {
    {"run", graph_run, METH_VARARGS,
     "run(callback=None, batch=256, budget=0) -> tasks executed by this call"},
    {"reset", graph_reset, METH_NOARGS,
     "rewind dependency counters and the ready stack for a replay"},
    {"done", graph_done, METH_NOARGS,
     "True when every task executed (and no error poisoned the run)"},
    {"failed", graph_failed, METH_NOARGS,
     "True when a body callback raised and poisoned the run"},
    {"pending", graph_pending, METH_NOARGS,
     "tasks not yet executed"},
    {"size", graph_size, METH_NOARGS,
     "(n_tasks, n_edges)"},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject GraphType = [] {
    PyTypeObject t = {PyVarObject_HEAD_INIT(nullptr, 0)};
    t.tp_name = "parsec_tpu._ptexec.Graph";
    t.tp_basicsize = sizeof(Graph);
    t.tp_flags = Py_TPFLAGS_DEFAULT;
    t.tp_doc = "flattened task graph executed by the native FSM lane";
    t.tp_new = graph_new;
    t.tp_dealloc = graph_dealloc;
    t.tp_methods = graph_methods;
    return t;
}();

PyModuleDef ptexec_module = {
    PyModuleDef_HEAD_INIT, "_ptexec",
    "native PTG execution lane (see native/src/ptexec.cpp)", -1,
    nullptr, nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__ptexec(void) {
    if (PyType_Ready(&GraphType) < 0) return nullptr;
    PyObject *m = PyModule_Create(&ptexec_module);
    if (!m) return nullptr;
    Py_INCREF(&GraphType);
    if (PyModule_AddObject(m, "Graph",
                           reinterpret_cast<PyObject *>(&GraphType)) < 0) {
        Py_DECREF(&GraphType);
        Py_DECREF(m);
        return nullptr;
    }
    return m;
}
