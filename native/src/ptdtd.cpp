// parsec_tpu._ptdtd — the DTD dependency engine as a CPython extension.
//
// Stands where the reference's C insert path stands
// (parsec/interfaces/dtd/insert_function.c:3617 parsec_dtd_insert_task ->
// parsec_dtd_set_params_of_task insert_function.c:2896 and the release walk
// parsec_dtd_ordering_correctly, insert_function_internal.h:277): runtime
// dependency discovery over per-tile last-writer/reader chains, the
// insertion-guard count-then-activate protocol, and the successor release
// that collects newly-ready tasks.
//
// Why a CPython extension and not ctypes: this is called ONCE PER TASK on
// the insert and completion hot paths; a ctypes boundary costs ~2 us while
// a C-extension method call costs ~0.2 us (measured in this container —
// see parsec_tpu/native.py's docstring for the ctypes numbers).
//
// TWO LANES share the chain state:
//
//  * the per-task lane (insert/activate/complete) — one C call per task,
//    ids surfaced to Python, which owns the task objects and runs bodies
//    through the ordinary scheduling FSM. v1 of this engine.
//  * the BATCHED lane (register_class/insert_many/drain_ready) — the
//    whole insert->link->ready->execute->release cycle stays inside the
//    engine in batches. insert_many() links N tasks under ONE GIL drop
//    (the count-then-activate protocol per task is preserved: the guard
//    is held across the link and dropped only once the task is fully
//    recorded — with the engine mutex held for the whole batch, a
//    concurrent complete() can never observe a half-linked task).
//    Ready batch-lane tasks never surface to Python as ids: drain_ready()
//    pops them, gathers their flow payloads from the per-tile payload
//    slots (Python owns the VALUES, C owns the slot lifetimes — the
//    ptexec data-mode split), invokes the class's batched callback once
//    per (class, batch), lands the written payloads back into the tile
//    slots, and feeds the release walk directly back into the ready
//    structure. Only per-task-lane successors released by a batch
//    completion come back to Python (the `surfaced` tuple).
//
// Scope: the SINGLE-RANK engine. Distributed inserts, the replay auditor,
// and remote version bookkeeping stay in the Python engine (dsl/dtd.py
// _link_tile) — they are protocol-bound, not insert-rate-bound. The Python
// side gates which engine (and which lane) a taskpool uses.
//
// Concurrency: chain/task/tile/ready state is guarded by an internal
// mutex (v1 relied on the GIL; insert_many drops the GIL for the link
// walk, so concurrent inserter threads now scale on real cores and every
// entry point locks). Python OBJECT references (tile payload slots, task
// value tuples, class callbacks) are only created/destroyed while the
// GIL is held; INCREFs may happen under the mutex but DECREFs (which can
// run arbitrary __del__) and allocations are always deferred until the
// mutex is released, so a finalizer can never re-enter the engine under
// its own lock. Task/tile records live in growing arrays; ids are
// indices and are never recycled (a completed task id may persist as a
// tile's last_writer).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <mutex>
#include <new>
#include <vector>

#include "ptcomm_iface.h"
#include "ptdev_iface.h"
#include "pthist.h"
#include "ptrace_ring.h"
#include "ptsched.h"

namespace {

constexpr int32_t ACC_READ = 0x1;    // mirrors dsl/dtd.py READ
constexpr int32_t ACC_WRITE = 0x2;   // mirrors dsl/dtd.py WRITE

// in-lane trace event keys (registered in the PBP dictionary by
// utils/native_trace.py; ring contract in ptrace_ring.h)
constexpr uint32_t EV_LINK = 1;   // one interval per insert_many link batch
constexpr uint32_t EV_EXEC = 2;   // one interval per (class, batch) dispatch
constexpr uint32_t EV_TASK = 3;   // one point per batch-lane task completion

// latency histogram slots (pthist.h; names mirrored in utils/hist.py)
constexpr int H_EXEC = 0;     // per-task (class,batch) latency, amortized
constexpr int H_READY = 1;    // batch-lane ready-push -> drain-pop wait
constexpr int N_HISTS = 2;
const char *const HIST_NAMES[N_HISTS] = {"exec_ns", "ready_wait_ns"};

constexpr Py_ssize_t PT_FLOWS_MAX = 64;

struct TaskRec {
    int32_t deps_remaining = 1;   // the insertion-in-progress guard
    bool completed = false;
    uint32_t stamp = 0;           // pred-dedup visit stamp
    int32_t cls = -1;             // batch-lane class id (-1: per-task lane)
    int64_t ready_ns = 0;         // ready-push stamp (histograms; under mu)
    int64_t flow_off = 0;         // into the flow arena (batch lane only)
    int32_t flow_n = 0;
    PyObject *vals = nullptr;     // by-value args tuple (batch lane, owned)
    std::vector<int64_t> succs;
};

struct TileRec {
    int64_t last_writer = -1;
    int32_t compact_at = 32;      // reader-list compaction watermark
    std::vector<int64_t> readers;
    PyObject *payload = nullptr;  // batch-lane payload slot (owned)
    int64_t writes = 0;           // batch-lane writes since last slot_sync
};

struct ClassRec {
    PyObject *cb = nullptr;            // batched callback (owned)
    PyObject *retire = nullptr;        // post-landing accounting cb (owned)
    std::vector<int32_t> argmap;       // body arg -> flow index, -1 = value
    std::vector<int32_t> accs;         // per-flow access bits
    int32_t nvals = 0;                 // count of -1 entries in argmap
    int32_t nwrites = 0;               // count of WRITE flows
    int32_t pool = -1;                 // scheduler-plane pool handle (the
                                       // QoS identity of the owning
                                       // taskpool; -1 = private ready)
    int32_t device = 0;                // 1 = device-bodied: ready tasks
                                       // surface onto the ptdev lane
                                       // (dev_bind) instead of `ready`
};

struct Engine {
    PyObject_HEAD
    std::mutex *mu;               // guards everything below except refcounts
    std::vector<TaskRec> *tasks;
    std::vector<TileRec> *tiles;
    std::vector<ClassRec> *classes;
    std::vector<int64_t> *flow_tile;   // batch-lane flow arena
    std::vector<int64_t> *flow_acc;
    std::vector<int64_t> *ready;       // ready batch-lane task ids (LIFO)
    uint32_t stamp;
    int64_t live;                 // inserted - completed
    int64_t batch_done;           // batch-lane tasks executed (diagnostics)
    bool poisoned;                // a batch callback raised
    // remote-ingest surfacing (the comm lane's ptdtd entry point): ready
    // PER-TASK-LANE tasks released by an arrived remote dep park here
    // until the next drain_ready() hands them to Python for scheduling
    std::vector<int64_t> *rsurf;
    std::atomic<int64_t> acts_rx;      // remote decrements ingested
    std::atomic<int64_t> ingest_bad;   // out-of-range/completed ids
    // in-lane event rings (null until trace_enable)
    std::atomic<ptrace_ring::State *> trace;
    // latency histograms (null until hist_enable)
    std::atomic<pthist::State<N_HISTS> *> hist;
    // scheduler plane (sched_bind, ISSUE 9): ready batch-lane tasks of
    // pool-bound classes enter the shared plane instead of `ready`, so N
    // concurrent DTD taskpools drain by DRR weight; classes without a
    // pool (plane off, pre-plane pools) keep the private vector
    ptsched::Plane *splane;
    PyObject *sched_cap;
    // device lane (dev_bind, ISSUE 10): ready tasks of device-marked
    // classes surface onto the ptdev lane's MPSC queue (GIL-free) and
    // come back through dev_retire() — wired at the engine level; the
    // Python DTD front end keeps device pools on the interpreted device
    // module this PR (counted ineligible), the ptcomm precedent
    bool dev_bound;
    uint32_t dev_pool;
    PtDevSubmitVtbl dsend;
    std::atomic<int64_t> dev_tx;
    std::atomic<int64_t> dev_done;
    std::atomic<int64_t> dev_bad;
};

PyObject *engine_new(PyTypeObject *type, PyObject *, PyObject *) {
    Engine *self = reinterpret_cast<Engine *>(type->tp_alloc(type, 0));
    if (!self) return nullptr;
    self->mu = new (std::nothrow) std::mutex();
    self->tasks = new (std::nothrow) std::vector<TaskRec>();
    self->tiles = new (std::nothrow) std::vector<TileRec>();
    self->classes = new (std::nothrow) std::vector<ClassRec>();
    self->flow_tile = new (std::nothrow) std::vector<int64_t>();
    self->flow_acc = new (std::nothrow) std::vector<int64_t>();
    self->ready = new (std::nothrow) std::vector<int64_t>();
    self->rsurf = new (std::nothrow) std::vector<int64_t>();
    self->stamp = 0;
    self->live = 0;
    self->batch_done = 0;
    self->poisoned = false;
    new (&self->acts_rx) std::atomic<int64_t>(0);
    new (&self->ingest_bad) std::atomic<int64_t>(0);
    new (&self->trace) std::atomic<ptrace_ring::State *>(nullptr);
    new (&self->hist) std::atomic<pthist::State<N_HISTS> *>(nullptr);
    self->splane = nullptr;
    self->sched_cap = nullptr;
    self->dev_bound = false;
    self->dev_pool = 0;
    self->dsend = PtDevSubmitVtbl{0, nullptr, nullptr};
    new (&self->dev_tx) std::atomic<int64_t>(0);
    new (&self->dev_done) std::atomic<int64_t>(0);
    new (&self->dev_bad) std::atomic<int64_t>(0);
    if (!self->mu || !self->tasks || !self->tiles || !self->classes ||
        !self->flow_tile || !self->flow_acc || !self->ready ||
        !self->rsurf) {
        Py_DECREF(self);
        PyErr_NoMemory();
        return nullptr;
    }
    return reinterpret_cast<PyObject *>(self);
}

void engine_dealloc(PyObject *obj) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    if (self->tasks)
        for (auto &t : *self->tasks) Py_XDECREF(t.vals);
    if (self->tiles)
        for (auto &t : *self->tiles) Py_XDECREF(t.payload);
    if (self->classes)
        for (auto &c : *self->classes) {
            Py_XDECREF(c.cb);
            Py_XDECREF(c.retire);
        }
    delete self->mu;
    delete self->tasks;
    delete self->tiles;
    delete self->classes;
    delete self->flow_tile;
    delete self->flow_acc;
    delete self->ready;
    delete self->rsurf;
    delete self->trace.load(std::memory_order_acquire);
    delete self->hist.load(std::memory_order_acquire);
    Py_CLEAR(self->sched_cap);   // pool handles are owned by the Python
    Py_TYPE(obj)->tp_free(obj);  // side (core/sched_plane.py unregisters)
}

// tile() -> int : register a new tile chain (payload slot starts empty)
PyObject *engine_tile(PyObject *obj, PyObject *) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    Py_ssize_t nid;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        self->tiles->emplace_back();
        nid = (Py_ssize_t)self->tiles->size() - 1;
    }
    return PyLong_FromSsize_t(nid);
}

// The chain-link walk shared by both lanes. MUST be called with mu held.
// Links one task's flows into the tile chains and returns its id with the
// insertion guard STILL HELD (deps_remaining = 1 + discovered preds).
//
// Replicates dsl/dtd.py _link_tile single-rank semantics exactly:
//   READ (or access without WRITE): RAW pred on the live last writer;
//     the task joins the tile's reader list (amortized compaction of
//     completed readers past the doubling watermark).
//   WRITE: WAR preds on live readers, WAW pred on the live last writer;
//     the tile chain then points at this task and the reader list resets.
// Preds are deduplicated (visit stamps) and self-edges skipped; each live
// pred gains a successor edge and bumps this task's dep count.
int64_t link_locked(Engine *self, const int64_t *tixs, const int64_t *laccs,
                    Py_ssize_t nflows) {
    std::vector<TaskRec> &tasks = *self->tasks;
    std::vector<TileRec> &tiles = *self->tiles;
    const int64_t tid = (int64_t)tasks.size();
    tasks.emplace_back();
    self->live++;
    if (++self->stamp == 0) {     // stamp wrapped: clear all (rare)
        for (auto &t : tasks) t.stamp = 0;
        self->stamp = 1;
    }
    const uint32_t stamp = self->stamp;
    int32_t new_deps = 0;

    for (Py_ssize_t i = 0; i < nflows; i++) {
        int64_t tix = tixs[i];
        int64_t acc = laccs[i];
        TileRec &tile = tiles[(size_t)tix];
        const bool is_read = (acc & ACC_READ) || !(acc & ACC_WRITE);
        if (is_read) {
            int64_t lw = tile.last_writer;
            if (lw >= 0 && !tasks[(size_t)lw].completed &&
                lw != tid && tasks[(size_t)lw].stamp != stamp) {
                tasks[(size_t)lw].stamp = stamp;
                tasks[(size_t)lw].succs.push_back(tid);
                new_deps++;
            }
            if (!(acc & ACC_WRITE)) {   // pure READ joins the reader list
                if ((int32_t)tile.readers.size() >= tile.compact_at) {
                    size_t w = 0;       // prune completed readers in place
                    for (size_t r = 0; r < tile.readers.size(); r++)
                        if (!tasks[(size_t)tile.readers[r]].completed)
                            tile.readers[w++] = tile.readers[r];
                    tile.readers.resize(w);
                    int32_t dbl = 2 * (int32_t)(w + 1);
                    tile.compact_at = dbl > 32 ? dbl : 32;
                }
                tile.readers.push_back(tid);
            }
        }
        if (acc & ACC_WRITE) {
            for (int64_t r : tile.readers) {
                if (r == tid) continue;
                TaskRec &rr = tasks[(size_t)r];
                if (!rr.completed && rr.stamp != stamp) {
                    rr.stamp = stamp;
                    rr.succs.push_back(tid);
                    new_deps++;
                }
            }
            int64_t lw = tile.last_writer;
            if (lw >= 0 && lw != tid) {
                TaskRec &lwr = tasks[(size_t)lw];
                if (!lwr.completed && lwr.stamp != stamp) {
                    lwr.stamp = stamp;
                    lwr.succs.push_back(tid);
                    new_deps++;
                }
            }
            tile.last_writer = tid;
            tile.readers.clear();
            tile.compact_at = 32;
        }
    }
    tasks[(size_t)tid].deps_remaining += new_deps;   // guard still held
    return tid;
}

// Push collected (pool, tid) ready pairs into the scheduler plane,
// contiguous same-pool runs in one plane call each — shared by the
// insert_many link batch and the drain_ready release walk. Call with
// NO engine mutex held (the plane has its own locks). ``scratch`` is a
// caller-owned reusable buffer: this runs on the GIL-dropped hot paths,
// which must not pay a malloc per pool run.
void flush_planeq(ptsched::Plane *spl,
                  std::vector<std::pair<int32_t, int32_t>> &planeq,
                  int wid, std::vector<int32_t> &scratch) {
    for (size_t i = 0; i < planeq.size();) {
        size_t j = i;
        int32_t ph = planeq[i].first;
        scratch.clear();
        while (j < planeq.size() && planeq[j].first == ph)
            scratch.push_back(planeq[j++].second);
        spl->push(ph, wid, scratch.data(), nullptr, (int)scratch.size());
        i = j;
    }
    planeq.clear();
}

// mu held (or GIL for readers: every classes mutator runs under mu AND
// the GIL). The scheduler-plane pool a batch class drains through, or -1.
// Plane ids are int32 — an id past 2^31 (weeks of sustained serving on
// one engine) falls back to the private ready vector rather than wrap.
inline int32_t plane_pool_of(Engine *self, int32_t cls, int64_t tid) {
    if (!self->splane || cls < 0 || tid > INT32_MAX) return -1;
    return (*self->classes)[(size_t)cls].pool;
}

// The release walk shared by both lanes. MUST be called with mu held.
// Marks `tid` completed and decrements its successors; newly-ready
// batch-lane successors go straight onto the internal ready structure —
// or, for plane-bound classes, into `planeq` (pool, tid32) pairs the
// caller pushes into the scheduler plane AFTER mu drops (null: pushed
// inline, the comm-ingest path) — and newly-ready per-task-lane
// successors are appended to `surfaced` for Python to schedule. ``now``
// (0 = histograms off) stamps ready pushes for the ready-wait histogram
// — captured once per caller batch.
void complete_locked(Engine *self, int64_t tid,
                     std::vector<int64_t> &surfaced, int64_t now = 0,
                     std::vector<std::pair<int32_t, int32_t>> *planeq =
                         nullptr) {
    std::vector<TaskRec> &tasks = *self->tasks;
    TaskRec &rec = tasks[(size_t)tid];
    rec.completed = true;
    self->live--;
    // admission accounting: the completing task leaves its pool's
    // in-flight window (one relaxed atomic; safe under mu)
    int32_t myp = plane_pool_of(self, rec.cls, tid);
    if (myp >= 0) self->splane->retired(myp, 1);
    // move out the successor list so the record sheds its heap storage
    std::vector<int64_t> succs;
    succs.swap(rec.succs);
    for (int64_t s : succs) {
        TaskRec &sr = tasks[(size_t)s];
        if (--sr.deps_remaining == 0) {
            if (sr.cls >= 0) {
                sr.ready_ns = now;
                if (self->dev_bound &&
                    (*self->classes)[(size_t)sr.cls].device &&
                    s <= INT32_MAX) {
                    // device-bodied class: surface onto the ptdev lane
                    // (lock-free submit; mu-held is fine, never blocks)
                    self->dsend.submit(self->dsend.dev, self->dev_pool,
                                       (int32_t)s);
                    self->dev_tx.fetch_add(1, std::memory_order_relaxed);
                    continue;
                }
                int32_t ph = plane_pool_of(self, sr.cls, s);
                if (ph >= 0) {
                    if (planeq) {
                        planeq->emplace_back(ph, (int32_t)s);
                    } else {
                        int32_t t32 = (int32_t)s;
                        self->splane->push(ph, -1, &t32, nullptr, 1);
                    }
                } else {
                    self->ready->push_back(s);
                }
            } else {
                surfaced.push_back(s);
            }
        }
    }
}

// one acquire load per engine entry point; disabled degrades to null
inline pthist::State<N_HISTS> *hist_of(Engine *self) {
    pthist::State<N_HISTS> *hs = self->hist.load(std::memory_order_acquire);
    if (hs && !hs->enabled.load(std::memory_order_relaxed)) hs = nullptr;
    return hs;
}

// insert(tile_ids: list|tuple[int], accs: list|tuple[int])
//   -> (task_id, deps_remaining)   — the insertion guard is STILL HELD
//
// The per-task lane. The insertion guard (count starts at 1) is NOT
// dropped here: the caller must publish its id->task bookkeeping and then
// call activate(task_id), which drops the guard — the count-then-activate
// protocol of parsec_dtd_schedule_task_if_ready (insert_function.c:2963).
// Dropping the guard inside insert() would let a fast predecessor
// completing on a worker thread surface this id from complete() BEFORE
// the inserting thread has mapped it (the round-5 activation race,
// ADVICE.md).
PyObject *engine_insert(PyObject *obj, PyObject *args) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    PyObject *tile_ids, *accs;
    if (!PyArg_ParseTuple(args, "OO", &tile_ids, &accs))
        return nullptr;
    // lists are what the hot caller builds; accept tuples too
    const bool til = PyList_Check(tile_ids), acl = PyList_Check(accs);
    if ((!til && !PyTuple_Check(tile_ids)) ||
        (!acl && !PyTuple_Check(accs))) {
        PyErr_SetString(PyExc_TypeError, "tile_ids/accs: list or tuple");
        return nullptr;
    }
    Py_ssize_t nflows = til ? PyList_GET_SIZE(tile_ids)
                            : PyTuple_GET_SIZE(tile_ids);
    if ((acl ? PyList_GET_SIZE(accs) : PyTuple_GET_SIZE(accs)) != nflows) {
        PyErr_SetString(PyExc_ValueError, "tile_ids/accs length mismatch");
        return nullptr;
    }

    // validate EVERYTHING before mutating any chain state: a mid-loop
    // failure after linking flow 0 would leave successor edges (and
    // possibly tile.last_writer) pointing at a popped — soon reused — id
    if (nflows > PT_FLOWS_MAX) {
        PyErr_SetString(PyExc_ValueError, "too many flows (max 64)");
        return nullptr;
    }
    int64_t tixs[PT_FLOWS_MAX];
    int64_t laccs[PT_FLOWS_MAX];
    // tiles->size() is read under the GIL without mu: tile ids only grow,
    // and a tile referenced here was necessarily created before this call
    size_t ntiles = self->tiles->size();
    for (Py_ssize_t i = 0; i < nflows; i++) {
        tixs[i] = PyLong_AsLongLong(
            til ? PyList_GET_ITEM(tile_ids, i)
                : PyTuple_GET_ITEM(tile_ids, i));
        laccs[i] = PyLong_AsLong(acl ? PyList_GET_ITEM(accs, i)
                                     : PyTuple_GET_ITEM(accs, i));
        if (!PyErr_Occurred() &&
            (tixs[i] < 0 || (size_t)tixs[i] >= ntiles))
            PyErr_SetString(PyExc_IndexError, "bad tile id");
        if (PyErr_Occurred()) return nullptr;
    }

    int64_t tid;
    int32_t held;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        tid = link_locked(self, tixs, laccs, nflows);
        held = (*self->tasks)[(size_t)tid].deps_remaining;
    }
    return Py_BuildValue("(Li)", (long long)tid, (int)held);
}

// activate(task_id) -> deps_remaining after dropping the insertion guard
// (0 == ready NOW and the caller owns scheduling it; a concurrent
// complete() can never have reported it). Call exactly once per insert,
// AFTER the id->task map is populated.
PyObject *engine_activate(PyObject *obj, PyObject *arg) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    int64_t tid = PyLong_AsLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    int32_t left;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        std::vector<TaskRec> &tasks = *self->tasks;
        if (tid < 0 || (size_t)tid >= tasks.size()) {
            PyErr_SetString(PyExc_IndexError, "bad task id");
            return nullptr;
        }
        TaskRec &rec = tasks[(size_t)tid];
        if (rec.completed || rec.cls >= 0) {
            PyErr_SetString(PyExc_RuntimeError,
                            rec.completed ? "activate after completion"
                                          : "activate on a batch-lane task");
            return nullptr;
        }
        left = --rec.deps_remaining;
    }
    return PyLong_FromLong(left);
}

// complete(task_id) -> tuple of newly-ready PER-TASK-LANE task ids (often
// empty). Newly-ready batch-lane successors are NOT surfaced: they join
// the engine's internal ready structure for the next drain_ready().
PyObject *engine_complete(PyObject *obj, PyObject *arg) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    int64_t tid = PyLong_AsLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    std::vector<int64_t> surfaced;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        std::vector<TaskRec> &tasks = *self->tasks;
        if (tid < 0 || (size_t)tid >= tasks.size()) {
            PyErr_SetString(PyExc_IndexError, "bad task id");
            return nullptr;
        }
        TaskRec &rec = tasks[(size_t)tid];
        if (rec.completed) {
            PyErr_SetString(PyExc_RuntimeError, "task completed twice");
            return nullptr;
        }
        if (rec.cls >= 0) {
            PyErr_SetString(PyExc_RuntimeError,
                            "complete() on a batch-lane task");
            return nullptr;
        }
        complete_locked(self, tid, surfaced,
                        hist_of(self) ? ptrace_ring::now_ns() : 0);
    }
    PyObject *tup = PyTuple_New((Py_ssize_t)surfaced.size());
    if (!tup) return nullptr;
    for (size_t i = 0; i < surfaced.size(); i++) {
        PyObject *v = PyLong_FromLongLong(surfaced[i]);
        if (!v) { Py_DECREF(tup); return nullptr; }
        PyTuple_SET_ITEM(tup, (Py_ssize_t)i, v);
    }
    return tup;
}

// ------------------------------------------------------------ batched lane

// register_class(callback, argmap, accs[, retire]) -> class id
//   callback(args_list) -> outs_list|None: runs the bodies for one batch.
//     args_list[i] is the i-th task's body-args tuple (payloads gathered
//     from the tile slots per argmap). For classes with WRITE flows the
//     callback must return a list whose i-th entry is a tuple with one
//     output per WRITE flow, in flow order (the Python side normalizes).
//   argmap: per body arg, the flow index it reads, or -1 for the next
//     entry of the task's by-value tuple.
//   accs: per-flow access bits (WRITE flows receive landed outputs).
//   retire(n): optional; called AFTER the batch's outputs have landed in
//     the tile slots and its release walk has run (drain_ready phase 3),
//     so execution-count consumers (wait()'s done predicate) can never
//     observe the counters ahead of the payloads.
PyObject *engine_register_class(PyObject *obj, PyObject *args) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    PyObject *cb, *argmap_o, *accs_o, *retire = Py_None;
    int pool = -1;     // scheduler-plane pool handle of the owning
                       // taskpool (QoS routing; -1 = private ready)
    int device = 0;    // 1 = device-bodied (ready tasks surface onto the
                       // ptdev lane once dev_bind armed it)
    if (!PyArg_ParseTuple(args, "OOO|Oii", &cb, &argmap_o, &accs_o, &retire,
                          &pool, &device))
        return nullptr;
    if (!PyCallable_Check(cb)) {
        PyErr_SetString(PyExc_TypeError, "callback must be callable");
        return nullptr;
    }
    if (retire != Py_None && !PyCallable_Check(retire)) {
        PyErr_SetString(PyExc_TypeError, "retire must be callable or None");
        return nullptr;
    }
    ClassRec cr;
    PyObject *fast = PySequence_Fast(argmap_o, "argmap: sequence of ints");
    if (!fast) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    for (Py_ssize_t i = 0; i < n; i++) {
        long v = PyLong_AsLong(PySequence_Fast_GET_ITEM(fast, i));
        if (v == -1 && PyErr_Occurred()) { Py_DECREF(fast); return nullptr; }
        cr.argmap.push_back((int32_t)v);
        if (v < 0) cr.nvals++;
    }
    Py_DECREF(fast);
    fast = PySequence_Fast(accs_o, "accs: sequence of ints");
    if (!fast) return nullptr;
    n = PySequence_Fast_GET_SIZE(fast);
    if (n > PT_FLOWS_MAX) {
        Py_DECREF(fast);
        PyErr_SetString(PyExc_ValueError, "too many flows (max 64)");
        return nullptr;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        long v = PyLong_AsLong(PySequence_Fast_GET_ITEM(fast, i));
        if (v == -1 && PyErr_Occurred()) { Py_DECREF(fast); return nullptr; }
        cr.accs.push_back((int32_t)v);
        if (v & ACC_WRITE) cr.nwrites++;
    }
    Py_DECREF(fast);
    for (int32_t a : cr.argmap) {
        if (a >= (int32_t)cr.accs.size()) {
            PyErr_SetString(PyExc_ValueError, "argmap flow index out of range");
            return nullptr;
        }
    }
    Py_INCREF(cb);
    cr.cb = cb;
    if (retire != Py_None) {
        Py_INCREF(retire);
        cr.retire = retire;
    }
    cr.pool = (pool >= 0 && pool < ptsched::MAX_POOLS) ? pool : -1;
    cr.device = device ? 1 : 0;
    Py_ssize_t cls;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        self->classes->push_back(cr);     // vector owns the cb reference now
        cls = (Py_ssize_t)self->classes->size() - 1;
    }
    return PyLong_FromSsize_t(cls);
}

// insert_many(specs) -> count
//   specs: list of per-task tuples (cls, vals_or_None, t0, a0, t1, a1, …).
//   Parses and validates everything under the GIL, then links the whole
//   batch with the GIL DROPPED (engine mutex held): concurrent inserter
//   threads overlap their link walks with body execution. Each task keeps
//   the count-then-activate protocol — the guard drops only after the
//   task's class/flow/value record is fully stored, inside the same
//   locked region, so a racing complete() can never surface a
//   half-inserted task.
PyObject *engine_insert_many(PyObject *obj, PyObject *arg) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    PyObject *fast = PySequence_Fast(arg, "specs: sequence");
    if (!fast) return nullptr;
    Py_ssize_t ntask = PySequence_Fast_GET_SIZE(fast);
    struct Spec { int32_t cls; int32_t nflows; int64_t foff; PyObject *vals; };
    std::vector<Spec> specs;
    specs.reserve((size_t)ntask);
    std::vector<int64_t> ftile, facc;   // local flow staging
    // tiles/classes sizes read under the GIL: ids only grow, and anything
    // referenced here was created before this call
    const size_t ntiles = self->tiles->size();
    const std::vector<ClassRec> &classes = *self->classes;
    bool bad = false;
    for (Py_ssize_t i = 0; i < ntask && !bad; i++) {
        PyObject *it = PySequence_Fast_GET_ITEM(fast, i);
        if (!PyTuple_Check(it)) { bad = true; break; }
        Py_ssize_t sz = PyTuple_GET_SIZE(it);
        if (sz < 2 || ((sz - 2) & 1)) { bad = true; break; }
        Py_ssize_t nf = (sz - 2) / 2;
        if (nf > PT_FLOWS_MAX) { bad = true; break; }
        long cls = PyLong_AsLong(PyTuple_GET_ITEM(it, 0));
        if (PyErr_Occurred() || cls < 0 ||
            (size_t)cls >= classes.size()) { bad = true; break; }
        PyObject *vals = PyTuple_GET_ITEM(it, 1);
        const ClassRec &cr = classes[(size_t)cls];
        if (vals == Py_None) {
            if (cr.nvals != 0) { bad = true; break; }
            vals = nullptr;
        } else {
            if (!PyTuple_Check(vals) ||
                PyTuple_GET_SIZE(vals) != cr.nvals) { bad = true; break; }
        }
        if ((Py_ssize_t)cr.accs.size() != nf) { bad = true; break; }
        Spec sp{(int32_t)cls, (int32_t)nf, (int64_t)ftile.size(), vals};
        for (Py_ssize_t k = 0; k < nf; k++) {
            int64_t tix = PyLong_AsLongLong(PyTuple_GET_ITEM(it, 2 + 2 * k));
            int64_t acc = PyLong_AsLong(PyTuple_GET_ITEM(it, 3 + 2 * k));
            if (PyErr_Occurred() || tix < 0 || (size_t)tix >= ntiles) {
                bad = true; break;
            }
            ftile.push_back(tix);
            facc.push_back(acc);
        }
        if (!bad) specs.push_back(sp);
    }
    if (bad) {
        Py_DECREF(fast);
        if (!PyErr_Occurred())
            PyErr_SetString(PyExc_ValueError, "malformed insert_many spec");
        return nullptr;
    }
    for (auto &sp : specs) Py_XINCREF(sp.vals);   // own across the link
    Py_DECREF(fast);   // specs' vals survive via the INCREF above

    // the whole batch links under ONE GIL drop
    ptrace_ring::Writer tw;
    tw.open(self->trace.load(std::memory_order_acquire));
    pthist::State<N_HISTS> *hs = hist_of(self);
    // plane-bound classes: ready pushes and admission bumps collect here
    // and land AFTER mu drops (the plane has its own locks); admitted
    // counts group per pool so a batch costs one admit() per pool
    std::vector<std::pair<int32_t, int32_t>> planeq;
    std::vector<std::pair<int32_t, int64_t>> admitted;
    std::vector<int32_t> pscratch;
    PyThreadState *ts = PyEval_SaveThread();
    if (tw.st) tw.rec(EV_LINK, (int64_t)ntask, ptrace_ring::FLAG_START);
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        std::vector<TaskRec> &tasks = *self->tasks;
        // ready-wait stamp, one clock read for the whole link batch
        const int64_t h_now = hs ? ptrace_ring::now_ns() : 0;
        const int64_t base = (int64_t)self->flow_tile->size();
        self->flow_tile->insert(self->flow_tile->end(), ftile.begin(),
                                ftile.end());
        self->flow_acc->insert(self->flow_acc->end(), facc.begin(),
                               facc.end());
        for (auto &sp : specs) {
            int64_t tid = link_locked(self, ftile.data() + sp.foff,
                                      facc.data() + sp.foff, sp.nflows);
            TaskRec &rec = tasks[(size_t)tid];
            rec.cls = sp.cls;
            rec.flow_off = base + sp.foff;
            rec.flow_n = sp.nflows;
            rec.vals = sp.vals;           // ownership moves to the record
            int32_t ph = plane_pool_of(self, sp.cls, tid);
            if (ph >= 0) {
                bool seen = false;
                for (auto &a : admitted)
                    if (a.first == ph) { a.second++; seen = true; break; }
                if (!seen) admitted.emplace_back(ph, 1);
            }
            // count-then-activate: the record is fully stored; drop the
            // guard. 0 deps -> straight onto the internal ready structure
            if (--rec.deps_remaining == 0) {
                rec.ready_ns = h_now;
                if (self->dev_bound &&
                    (*self->classes)[(size_t)sp.cls].device &&
                    tid <= INT32_MAX) {
                    self->dsend.submit(self->dsend.dev, self->dev_pool,
                                       (int32_t)tid);
                    self->dev_tx.fetch_add(1, std::memory_order_relaxed);
                } else if (ph >= 0) {
                    planeq.emplace_back(ph, (int32_t)tid);
                } else {
                    self->ready->push_back(tid);
                }
            }
        }
    }
    for (auto &a : admitted) self->splane->admit(a.first, a.second);
    if (!planeq.empty()) flush_planeq(self->splane, planeq, -1, pscratch);
    if (tw.st) tw.rec(EV_LINK, (int64_t)ntask, ptrace_ring::FLAG_END);
    PyEval_RestoreThread(ts);
    return PyLong_FromSsize_t(ntask);
}

// drain_ready(max_batch=256, budget=4096) -> (n_executed, surfaced)
//
// The in-lane ready-drain: pops ready batch-lane tasks, groups them by
// class, gathers each task's body args from the tile payload slots,
// invokes the class callback ONCE per (class, batch), lands written
// payloads back into the slots, and feeds the release walk straight back
// into the ready structure — intermediate ids never surface to Python.
// Newly-ready per-task-lane successors are returned in `surfaced` for
// the caller to schedule. Returns promptly when no batch-lane work is
// ready. Called with the GIL held; the callback runs with the GIL held
// and the engine mutex RELEASED (bodies may re-enter insert paths).
PyObject *engine_drain_ready(PyObject *obj, PyObject *args) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    int max_batch = 256;
    long long budget = 4096;
    int wid = 0;    // worker id — scheduler-plane hot-queue affinity
    if (!PyArg_ParseTuple(args, "|iLi", &max_batch, &budget, &wid))
        return nullptr;
    if (max_batch <= 0) max_batch = 256;
    long long total = 0;
    ptrace_ring::Writer tw;
    tw.open(self->trace.load(std::memory_order_acquire));
    pthist::State<N_HISTS> *hs = hist_of(self);
    std::vector<int64_t> surfaced;
    // (cls, tid) pairs: cls is snapshotted while the pops hold the mutex —
    // a concurrent insert_many links with the GIL DROPPED (mutex held) and
    // may reallocate the tasks vector, so the sort below must never
    // dereference it unlocked
    std::vector<std::pair<int32_t, int64_t>> local;
    std::vector<PyObject *> argrefs, defer_decref;
    std::vector<int32_t> accs_snap, argmap_snap;
    // scheduler plane: mixed-pool pops (hot queue -> weighted-DRR refill
    // -> steal), arbitrating across every registered DTD taskpool; the
    // per-class grouping below then batches them regardless of pool.
    // Releases push back with this worker's identity after mu drops.
    ptsched::Plane *const spl = self->splane;
    std::vector<ptsched::Item> pitems;
    std::vector<std::pair<int32_t, int32_t>> planeq;
    std::vector<int32_t> pscratch;
    if (spl) pitems.resize((size_t)max_batch);
    for (;;) {
        local.clear();
        int pgot = 0;
        if (spl)
            pgot = spl->pop(wid, ptsched::KIND_PTDTD, -1, pitems.data(),
                            max_batch);
        {
            std::lock_guard<std::mutex> lk(*self->mu);
            if (self->poisoned) break;   // popped ids die with the engine
            const int64_t h_now = hs ? ptrace_ring::now_ns() : 0;
            if (pgot) {
                for (int k = 0; k < pgot; k++) {
                    int64_t tid = (int64_t)pitems[(size_t)k].tid;
                    TaskRec &rec = (*self->tasks)[(size_t)tid];
                    if (h_now && rec.ready_ns > 0)
                        hs->h[H_READY].add(h_now - rec.ready_ns);
                    local.emplace_back(rec.cls, tid);
                }
            } else {
                if (self->ready->empty()) break;
                size_t take =
                    std::min((size_t)max_batch, self->ready->size());
                for (size_t k = self->ready->size() - take;
                     k < self->ready->size(); k++) {
                    int64_t tid = (*self->ready)[k];
                    TaskRec &rec = (*self->tasks)[(size_t)tid];
                    if (h_now && rec.ready_ns > 0)
                        hs->h[H_READY].add(h_now - rec.ready_ns);
                    local.emplace_back(rec.cls, tid);
                }
                self->ready->resize(self->ready->size() - take);
            }
        }
        // group by class so each callback sees one homogeneous batch; the
        // snapshot pairs keep the comparator off the live tasks vector
        std::stable_sort(local.begin(), local.end(),
                         [](const std::pair<int32_t, int64_t> &a,
                            const std::pair<int32_t, int64_t> &b) {
                             return a.first < b.first;
                         });
        size_t gi = 0;
        while (gi < local.size()) {
            size_t gj = gi;
            const int32_t cls = local[gi].first;
            while (gj < local.size() && local[gj].first == cls)
                gj++;
            const size_t gn = gj - gi;
            // snapshot the class record: the callback releases the GIL, so
            // a concurrent register_class may reallocate the vector —
            // references into it must not be held across the dispatch
            // (reading it GIL-held needs no mutex: every classes mutator
            // runs under the GIL and never drops it)
            PyObject *cb, *retire;
            int32_t nwrites;
            {
                const ClassRec &cr = (*self->classes)[(size_t)cls];
                cb = cr.cb;
                if (!cb) {
                    // release_pool() already dropped this class: its pool
                    // completed, so no task of it can be ready — seeing one
                    // means the caller broke the hand-off contract
                    PyErr_SetString(PyExc_RuntimeError,
                                    "batch class released with tasks "
                                    "still outstanding");
                    std::lock_guard<std::mutex> lk(*self->mu);
                    self->poisoned = true;
                    return nullptr;
                }
                Py_INCREF(cb);
                retire = cr.retire;
                Py_XINCREF(retire);
                nwrites = cr.nwrites;
                accs_snap = cr.accs;
                argmap_snap = cr.argmap;
            }
            const size_t nargs = argmap_snap.size();
            // phase 1 (mutex held): snapshot payload/value references with
            // bare INCREFs — no allocation, no arbitrary code under mu
            argrefs.clear();
            argrefs.reserve(gn * nargs);
            {
                std::lock_guard<std::mutex> lk(*self->mu);
                for (size_t t = gi; t < gj; t++) {
                    TaskRec &rec = (*self->tasks)[(size_t)local[t].second];
                    int32_t vi = 0;
                    for (size_t a = 0; a < nargs; a++) {
                        PyObject *v;
                        int32_t f = argmap_snap[a];
                        if (f < 0) {
                            v = rec.vals
                                ? PyTuple_GET_ITEM(rec.vals, vi) : Py_None;
                            vi++;
                        } else {
                            int64_t tix =
                                (*self->flow_tile)[(size_t)(rec.flow_off + f)];
                            v = (*self->tiles)[(size_t)tix].payload;
                            if (!v) v = Py_None;
                        }
                        Py_INCREF(v);
                        argrefs.push_back(v);
                    }
                }
            }
            // phase 2 (mutex released): build the args list and dispatch
            const int64_t h_t0 = hs ? ptrace_ring::now_ns() : 0;
            if (tw.st) tw.rec(EV_EXEC, cls, ptrace_ring::FLAG_START);
            PyObject *args_list = PyList_New((Py_ssize_t)gn);
            PyObject *outs = nullptr;
            size_t consumed = 0;       // argref rows moved into tuples
            if (args_list) {
                bool ok = true;
                for (size_t t = 0; t < gn; t++) {
                    PyObject *tp = PyTuple_New((Py_ssize_t)nargs);
                    if (!tp) { ok = false; break; }
                    for (size_t a = 0; a < nargs; a++)
                        PyTuple_SET_ITEM(tp, (Py_ssize_t)a,
                                         argrefs[t * nargs + a]);
                    consumed = t + 1;
                    PyList_SET_ITEM(args_list, (Py_ssize_t)t, tp);
                }
                if (ok)
                    outs = PyObject_CallFunctionObjArgs(cb, args_list,
                                                        nullptr);
            }
            // drop any refs a failed allocation left unconsumed
            for (size_t r = consumed * nargs; r < argrefs.size(); r++)
                Py_DECREF(argrefs[r]);
            Py_DECREF(cb);
            if (!outs) {
                Py_XDECREF(retire);
                // the callback raised (or allocation failed): poison the
                // lane so peers stop draining and propagate the exception
                Py_XDECREF(args_list);
                std::lock_guard<std::mutex> lk(*self->mu);
                self->poisoned = true;
                return nullptr;
            }
            if (nwrites) {
                bool shape_ok = PyList_Check(outs) &&
                                PyList_GET_SIZE(outs) == (Py_ssize_t)gn;
                for (Py_ssize_t t = 0; shape_ok && t < (Py_ssize_t)gn; t++) {
                    PyObject *o = PyList_GET_ITEM(outs, t);
                    shape_ok = PyTuple_Check(o) &&
                               PyTuple_GET_SIZE(o) >= (Py_ssize_t)nwrites;
                }
                if (!shape_ok) {
                    Py_XDECREF(retire);
                    Py_DECREF(args_list);
                    Py_DECREF(outs);
                    PyErr_SetString(PyExc_TypeError,
                                    "batch callback must return one output "
                                    "tuple per task (one item per WRITE "
                                    "flow)");
                    std::lock_guard<std::mutex> lk(*self->mu);
                    self->poisoned = true;
                    return nullptr;
                }
            }
            // phase 3 (mutex held): land written payloads into the tile
            // slots and run the release walk; DECREFs are deferred
            defer_decref.clear();
            {
                std::lock_guard<std::mutex> lk(*self->mu);
                const int64_t h_now = hs ? ptrace_ring::now_ns() : 0;
                for (size_t t = gi; t < gj; t++) {
                    TaskRec &rec = (*self->tasks)[(size_t)local[t].second];
                    if (nwrites) {
                        PyObject *out_t =
                            PyList_GET_ITEM(outs, (Py_ssize_t)(t - gi));
                        Py_ssize_t oi = 0;
                        for (size_t f = 0; f < accs_snap.size(); f++) {
                            if (!(accs_snap[f] & ACC_WRITE)) continue;
                            PyObject *nv = PyTuple_GET_ITEM(out_t, oi++);
                            int64_t tix = (*self->flow_tile)
                                [(size_t)(rec.flow_off + (int64_t)f)];
                            TileRec &tile = (*self->tiles)[(size_t)tix];
                            Py_INCREF(nv);
                            if (tile.payload)
                                defer_decref.push_back(tile.payload);
                            tile.payload = nv;
                            tile.writes++;
                        }
                    }
                    if (rec.vals) {
                        defer_decref.push_back(rec.vals);
                        rec.vals = nullptr;
                    }
                    if (tw.st)
                        tw.rec(EV_TASK, local[t].second,
                               ptrace_ring::FLAG_POINT);
                    complete_locked(self, local[t].second, surfaced, h_now,
                                    spl ? &planeq : nullptr);
                }
                self->batch_done += (int64_t)gn;
            }
            if (!planeq.empty())
                // newly-ready plane tasks from this batch's release walk
                // enter with this worker's hot-queue affinity
                flush_planeq(spl, planeq, wid, pscratch);
            if (hs) {
                // per-task (class, batch) latency: gather + dispatch +
                // landing + release amortized over the batch
                int64_t per =
                    (ptrace_ring::now_ns() - h_t0) / (int64_t)gn;
                hs->h[H_EXEC].add(per, gn);
            }
            if (tw.st) tw.rec(EV_EXEC, cls, ptrace_ring::FLAG_END);
            for (PyObject *p : defer_decref) Py_DECREF(p);
            Py_DECREF(args_list);
            Py_DECREF(outs);
            // retire AFTER phase 3: the pool's execution counters must
            // trail the payload landing, or a waiter observing
            // "executed == target" could sync stale slots
            if (retire) {
                PyObject *rr =
                    PyObject_CallFunction(retire, "n", (Py_ssize_t)gn);
                Py_DECREF(retire);
                if (!rr) {
                    std::lock_guard<std::mutex> lk(*self->mu);
                    self->poisoned = true;
                    return nullptr;
                }
                Py_DECREF(rr);
            }
            total += (long long)gn;
            gi = gj;
        }
        if (budget > 0 && total >= budget) break;
    }
    {
        // hand over per-task-lane tasks a remote ingest released since
        // the last drain (ingest_act runs on the comm progress thread
        // and cannot schedule Python tasks itself)
        std::lock_guard<std::mutex> lk(*self->mu);
        if (!self->rsurf->empty()) {
            surfaced.insert(surfaced.end(), self->rsurf->begin(),
                            self->rsurf->end());
            self->rsurf->clear();
        }
    }
    PyObject *sur = PyTuple_New((Py_ssize_t)surfaced.size());
    if (!sur) return nullptr;
    for (size_t i = 0; i < surfaced.size(); i++) {
        PyObject *v = PyLong_FromLongLong(surfaced[i]);
        if (!v) { Py_DECREF(sur); return nullptr; }
        PyTuple_SET_ITEM(sur, (Py_ssize_t)i, v);
    }
    PyObject *res = Py_BuildValue("(LN)", total, sur);
    if (!res) Py_DECREF(sur);
    return res;
}

// ------------------------------------------------------ tile payload slots

// slot_set(tile_id, payload) — seed/refresh a tile's payload slot (does
// NOT count as a batch-lane write: the per-task lane bumps its own
// versions Python-side and mirrors the value here for batch readers)
PyObject *engine_slot_set(PyObject *obj, PyObject *args) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    PyObject *payload;
    long long nid;
    if (!PyArg_ParseTuple(args, "LO", &nid, &payload))
        return nullptr;
    PyObject *old;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        if (nid < 0 || (size_t)nid >= self->tiles->size()) {
            PyErr_SetString(PyExc_IndexError, "bad tile id");
            return nullptr;
        }
        TileRec &tile = (*self->tiles)[(size_t)nid];
        Py_INCREF(payload);
        old = tile.payload;
        tile.payload = payload;
    }
    Py_XDECREF(old);
    Py_RETURN_NONE;
}

// slot_get(tile_id) -> payload or None (no bookkeeping side effects)
PyObject *engine_slot_get(PyObject *obj, PyObject *arg) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    long long nid = PyLong_AsLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    PyObject *p;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        if (nid < 0 || (size_t)nid >= self->tiles->size()) {
            PyErr_SetString(PyExc_IndexError, "bad tile id");
            return nullptr;
        }
        p = (*self->tiles)[(size_t)nid].payload;
        if (!p) p = Py_None;
        Py_INCREF(p);
    }
    return p;
}

// slot_sync(tile_id) -> (payload_or_None, writes_since_last_sync)
// Resets the write counter AND empties the slot (payload ownership moves
// to the returned tuple): after a sync the tile's HOST copy is
// authoritative again, so user updates to tile.data between quiescence
// points are honored — the flush path re-seeds empty slots from
// tile.data before the next batch links (dtd.py _flush_batch_locked).
// A retained slot here would silently outrank a post-wait() reseed.
PyObject *engine_slot_sync(PyObject *obj, PyObject *arg) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    long long nid = PyLong_AsLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    PyObject *p;
    long long w;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        if (nid < 0 || (size_t)nid >= self->tiles->size()) {
            PyErr_SetString(PyExc_IndexError, "bad tile id");
            return nullptr;
        }
        TileRec &tile = (*self->tiles)[(size_t)nid];
        p = tile.payload;            // ownership moves to the result
        tile.payload = nullptr;
        if (!p) { p = Py_None; Py_INCREF(p); }
        w = tile.writes;
        tile.writes = 0;
    }
    PyObject *res = Py_BuildValue("(NL)", p, w);
    if (!res) Py_DECREF(p);
    return res;
}

// release_pool(tile_ids, class_ids) — drop the engine-side references a
// completed pool pinned: tile payload slots and class callbacks. The
// Engine is per-CONTEXT while pools come and go, so without this every
// dead pool's payloads (and, through the callback closures, the pool
// object itself) would live until context teardown. Only legal once the
// pool is fully drained: a released class's tasks must never be ready.
PyObject *engine_release_pool(PyObject *obj, PyObject *args) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    PyObject *tiles_o, *classes_o;
    if (!PyArg_ParseTuple(args, "OO", &tiles_o, &classes_o))
        return nullptr;
    // parse ids BEFORE taking the mutex (no Python calls under mu)
    std::vector<int64_t> tids, cids;
    for (int pass = 0; pass < 2; pass++) {
        PyObject *src = pass ? classes_o : tiles_o;
        std::vector<int64_t> &dst = pass ? cids : tids;
        PyObject *fast = PySequence_Fast(src, "release_pool: sequence of ids");
        if (!fast) return nullptr;
        Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
        for (Py_ssize_t i = 0; i < n; i++) {
            int64_t v =
                PyLong_AsLongLong(PySequence_Fast_GET_ITEM(fast, i));
            if (v == -1 && PyErr_Occurred()) { Py_DECREF(fast); return nullptr; }
            dst.push_back(v);
        }
        Py_DECREF(fast);
    }
    std::vector<PyObject *> defer_decref;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        for (int64_t nid : tids) {
            if (nid < 0 || (size_t)nid >= self->tiles->size()) {
                PyErr_SetString(PyExc_IndexError, "bad tile id");
                goto fail;
            }
            TileRec &tile = (*self->tiles)[(size_t)nid];
            if (tile.payload) {
                defer_decref.push_back(tile.payload);
                tile.payload = nullptr;
            }
            tile.writes = 0;
        }
        for (int64_t cid : cids) {
            if (cid < 0 || (size_t)cid >= self->classes->size()) {
                PyErr_SetString(PyExc_IndexError, "bad class id");
                goto fail;
            }
            ClassRec &cr = (*self->classes)[(size_t)cid];
            if (cr.cb) {
                defer_decref.push_back(cr.cb);
                cr.cb = nullptr;
            }
            if (cr.retire) {
                defer_decref.push_back(cr.retire);
                cr.retire = nullptr;
            }
            // the plane pool slot may be reused after the Python side
            // unregisters it — a dead class must never route there
            cr.pool = -1;
        }
    }
    for (PyObject *p : defer_decref) Py_DECREF(p);
    Py_RETURN_NONE;
fail:
    for (PyObject *p : defer_decref) Py_DECREF(p);
    return nullptr;
}

// ------------------------------------------------------------- diagnostics

// successors(task_id) -> tuple of successor ids discovered so far.
// Complete BEFORE calling complete() on the task: the release walk moves
// the list out. Instrumentation consumers (the DOT grapher's PINS hook)
// mirror these onto the Python task so the native lane's DAG stays
// observable without re-running the discovery in Python.
PyObject *engine_successors(PyObject *obj, PyObject *arg) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    int64_t tid = PyLong_AsLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    std::vector<int64_t> succs;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        if (tid < 0 || (size_t)tid >= self->tasks->size()) {
            PyErr_SetString(PyExc_IndexError, "bad task id");
            return nullptr;
        }
        succs = (*self->tasks)[(size_t)tid].succs;
    }
    PyObject *tup = PyTuple_New((Py_ssize_t)succs.size());
    if (!tup) return nullptr;
    for (size_t i = 0; i < succs.size(); i++) {
        PyObject *v = PyLong_FromLongLong(succs[i]);
        if (!v) { Py_DECREF(tup); return nullptr; }
        PyTuple_SET_ITEM(tup, (Py_ssize_t)i, v);
    }
    return tup;
}

// ------------------------------------------------------- in-lane tracing

PyObject *engine_trace_enable(PyObject *obj, PyObject *args) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    return ptrace_ring::py_trace_enable(self->trace, args);
}

PyObject *engine_trace_disable(PyObject *obj, PyObject *) {
    return ptrace_ring::py_trace_disable(
        reinterpret_cast<Engine *>(obj)->trace.load(
            std::memory_order_acquire));
}

PyObject *engine_trace_drain(PyObject *obj, PyObject *) {
    return ptrace_ring::py_trace_drain(
        reinterpret_cast<Engine *>(obj)->trace.load(
            std::memory_order_acquire));
}

PyObject *engine_trace_dropped(PyObject *obj, PyObject *) {
    return ptrace_ring::py_trace_dropped(
        reinterpret_cast<Engine *>(obj)->trace.load(
            std::memory_order_acquire));
}

PyObject *engine_monotonic_ns(PyObject *, PyObject *) {
    return PyLong_FromLongLong(ptrace_ring::now_ns());
}

// --------------------------------------------------- latency histograms

PyObject *engine_hist_enable(PyObject *obj, PyObject *) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    PyObject *r = pthist::py_hist_enable<N_HISTS>(self->hist);
    if (!r) return nullptr;
    // tasks already awaiting drain get a real push stamp
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        int64_t now = ptrace_ring::now_ns();
        for (int64_t t : *self->ready)
            (*self->tasks)[(size_t)t].ready_ns = now;
    }
    return r;
}

PyObject *engine_hist_disable(PyObject *obj, PyObject *) {
    return pthist::py_hist_disable<N_HISTS>(
        reinterpret_cast<Engine *>(obj)->hist.load(
            std::memory_order_acquire));
}

PyObject *engine_hist_snapshot(PyObject *obj, PyObject *) {
    return pthist::py_hist_snapshot<N_HISTS>(
        reinterpret_cast<Engine *>(obj)->hist.load(
            std::memory_order_acquire),
        HIST_NAMES);
}

// deps_remaining(task_id) -> int  (diagnostics / paranoid checks)
PyObject *engine_deps_remaining(PyObject *obj, PyObject *arg) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    int64_t tid = PyLong_AsLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    std::lock_guard<std::mutex> lk(*self->mu);
    if (tid < 0 || (size_t)tid >= self->tasks->size()) {
        PyErr_SetString(PyExc_IndexError, "bad task id");
        return nullptr;
    }
    return PyLong_FromLong((*self->tasks)[(size_t)tid].deps_remaining);
}

PyObject *engine_pending(PyObject *obj, PyObject *) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    return PyLong_FromLongLong(self->live);
}

PyObject *engine_ready_count(PyObject *obj, PyObject *) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    int64_t plane_q = self->splane
        ? self->splane->queued_kind(ptsched::KIND_PTDTD) : 0;
    std::lock_guard<std::mutex> lk(*self->mu);
    return PyLong_FromLongLong((long long)self->ready->size() + plane_q);
}

PyObject *engine_batch_executed(PyObject *obj, PyObject *) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    return PyLong_FromLongLong(self->batch_done);
}

PyObject *engine_sizes(PyObject *obj, PyObject *) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    return Py_BuildValue("(nn)", (Py_ssize_t)self->tasks->size(),
                         (Py_ssize_t)self->tiles->size());
}

// ------------------------------------------------------- comm lane ingest

// GIL-free entry the comm progress thread calls through the
// PtCommIngestVtbl capsule: one arrived remote dep-release for task
// `tid`. A newly-ready batch-lane task joins the internal ready
// structure (next drain_ready executes it); a per-task-lane task parks
// in `rsurf` until drain_ready surfaces it for Python scheduling.
void dtd_ingest_act_c(void *obj, int32_t tid) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    if (tid < 0 || (size_t)tid >= self->tasks->size()) {
        self->ingest_bad.fetch_add(1, std::memory_order_relaxed);
        return;
    }
    TaskRec &rec = (*self->tasks)[(size_t)tid];
    if (rec.completed) {
        self->ingest_bad.fetch_add(1, std::memory_order_relaxed);
        return;
    }
    self->acts_rx.fetch_add(1, std::memory_order_relaxed);
    if (--rec.deps_remaining == 0) {
        if (rec.cls >= 0) {
            rec.ready_ns = hist_of(self) ? ptrace_ring::now_ns() : 0;
            int32_t ph = plane_pool_of(self, rec.cls, tid);
            if (ph >= 0) {
                int32_t t32 = (int32_t)tid;
                self->splane->push(ph, -1, &t32, nullptr, 1);
            } else {
                self->ready->push_back(tid);
            }
        } else {
            self->rsurf->push_back(tid);
        }
    }
}

void dtd_ingest_capsule_free(PyObject *cap) {
    std::free(PyCapsule_GetPointer(cap, PTCOMM_INGEST_CAPSULE));
}

PyObject *engine_ingest_capsule(PyObject *obj, PyObject *) {
    PtCommIngestVtbl *v =
        static_cast<PtCommIngestVtbl *>(std::malloc(sizeof(PtCommIngestVtbl)));
    if (!v) return PyErr_NoMemory();
    v->abi = PTCOMM_ABI;
    v->obj = obj;
    v->act = dtd_ingest_act_c;
    v->rdv_begin = nullptr;   // DTD payloads land through the tile/slot
    v->rdv_land = nullptr;    // machinery, not per-slot gates
    PyObject *cap = PyCapsule_New(v, PTCOMM_INGEST_CAPSULE,
                                  dtd_ingest_capsule_free);
    if (!cap) std::free(v);
    return cap;
}

PyObject *engine_ingest(PyObject *obj, PyObject *arg) {
    long long tid = PyLong_AsLongLong(arg);
    if (tid == -1 && PyErr_Occurred()) return nullptr;
    dtd_ingest_act_c(obj, (int32_t)tid);
    Py_RETURN_NONE;
}

// ------------------------------------------------------- device lane bind

// GIL-free entry the ptdev manager thread calls through the
// PtDevRetireVtbl capsule: device task `tid` completed (its outputs were
// already landed into the tile payload slots by the manager's poll
// callback, under the GIL, BEFORE this call). Runs the release walk:
// newly-ready device-class successors surface back onto the lane inside
// complete_locked, batch-lane successors join the internal ready
// structure, and per-task-lane successors park in rsurf for the next
// drain_ready — the same three-way routing a batch completion does.
void dtd_dev_retire_c(void *obj, int32_t tid) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    if (tid < 0 || (size_t)tid >= self->tasks->size() || !self->dev_bound) {
        self->dev_bad.fetch_add(1, std::memory_order_relaxed);
        return;
    }
    TaskRec &rec = (*self->tasks)[(size_t)tid];
    if (rec.completed || rec.cls < 0 ||
        !(*self->classes)[(size_t)rec.cls].device) {
        self->dev_bad.fetch_add(1, std::memory_order_relaxed);
        return;
    }
    complete_locked(self, tid, *self->rsurf,
                    hist_of(self) ? ptrace_ring::now_ns() : 0);
    self->batch_done++;
    self->dev_done.fetch_add(1, std::memory_order_relaxed);
}

void dtd_dev_retire_capsule_free(PyObject *cap) {
    std::free(PyCapsule_GetPointer(cap, PTDEV_RETIRE_CAPSULE));
}

PyObject *engine_dev_retire_capsule(PyObject *obj, PyObject *) {
    PtDevRetireVtbl *v =
        static_cast<PtDevRetireVtbl *>(std::malloc(sizeof(PtDevRetireVtbl)));
    if (!v) return PyErr_NoMemory();
    v->abi = PTDEV_ABI;
    v->obj = obj;
    v->retire = dtd_dev_retire_c;
    PyObject *cap = PyCapsule_New(v, PTDEV_RETIRE_CAPSULE,
                                  dtd_dev_retire_capsule_free);
    if (!cap) std::free(v);
    return cap;
}

// dev_bind(submit_capsule, dev_pool) — arm the device lane: ready tasks
// of device-marked classes (register_class(..., device=1)) surface onto
// the ptdev lane from this point on. Bind BEFORE inserting any task of a
// device class — an already-ready device task would otherwise sit in the
// internal ready structure and run through drain_ready's CPU callback.
PyObject *engine_dev_bind(PyObject *obj, PyObject *args) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    PyObject *cap;
    unsigned int pool;
    if (!PyArg_ParseTuple(args, "OI", &cap, &pool)) return nullptr;
    PtDevSubmitVtbl *sv = static_cast<PtDevSubmitVtbl *>(
        PyCapsule_GetPointer(cap, PTDEV_SUBMIT_CAPSULE));
    if (!sv) return nullptr;
    if (sv->abi != PTDEV_ABI) {
        PyErr_SetString(PyExc_RuntimeError, "ptdev ABI mismatch");
        return nullptr;
    }
    std::lock_guard<std::mutex> lk(*self->mu);
    if (self->dev_bound) {
        PyErr_SetString(PyExc_RuntimeError, "engine already dev-bound");
        return nullptr;
    }
    self->dsend = *sv;
    self->dev_pool = pool;
    self->dev_bound = true;
    Py_RETURN_NONE;
}

PyObject *engine_dev_retire(PyObject *obj, PyObject *arg) {
    long long tid = PyLong_AsLongLong(arg);
    if (tid == -1 && PyErr_Occurred()) return nullptr;
    dtd_dev_retire_c(obj, (int32_t)tid);
    Py_RETURN_NONE;
}

PyObject *engine_dev_stats(PyObject *obj, PyObject *) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    return Py_BuildValue(
        "{s:L,s:L,s:L}",
        "dev_tx", (long long)self->dev_tx.load(std::memory_order_relaxed),
        "dev_done",
        (long long)self->dev_done.load(std::memory_order_relaxed),
        "dev_bad", (long long)self->dev_bad.load(std::memory_order_relaxed));
}

// --------------------------------------------------- scheduler plane bind

// sched_bind(plane_capsule) — attach the shared scheduler plane: classes
// registered with a pool handle then route their ready tasks through it
// (drain_ready pops arbitrate across pools by DRR weight). Idempotent
// for the same plane; the engine is per-context and the plane per-context
// too, so a second different plane is a caller bug.
PyObject *engine_sched_bind(PyObject *obj, PyObject *arg) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    ptsched::Plane *pl = ptsched::plane_from_capsule(arg);
    if (!pl) return nullptr;
    if (self->splane && self->splane != pl) {
        PyErr_SetString(PyExc_RuntimeError,
                        "engine already bound to another scheduler plane");
        return nullptr;
    }
    if (!self->splane) {
        Py_INCREF(arg);
        self->sched_cap = arg;
        self->splane = pl;
    }
    Py_RETURN_NONE;
}

PyObject *engine_sched_bound(PyObject *obj, PyObject *) {
    return PyBool_FromLong(
        reinterpret_cast<Engine *>(obj)->splane != nullptr ? 1 : 0);
}

PyObject *engine_comm_stats(PyObject *obj, PyObject *) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    long long rs;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        rs = (long long)self->rsurf->size();
    }
    return Py_BuildValue(
        "{s:L,s:L,s:L}",
        "acts_rx", (long long)self->acts_rx.load(std::memory_order_relaxed),
        "ingest_bad",
        (long long)self->ingest_bad.load(std::memory_order_relaxed),
        "rsurf_pending", rs);
}

PyMethodDef engine_methods[] = {
    {"tile", engine_tile, METH_NOARGS,
     "register a tile chain; returns its id"},
    {"insert", engine_insert, METH_VARARGS,
     "insert(tile_ids, accs) -> (task_id, deps_remaining); the insertion "
     "guard stays held until activate(task_id)"},
    {"activate", engine_activate, METH_O,
     "drop the insertion guard; returns deps remaining (0 = ready now)"},
    {"complete", engine_complete, METH_O,
     "complete(task_id) -> tuple of newly-ready per-task-lane ids"},
    {"register_class", engine_register_class, METH_VARARGS,
     "register_class(callback, argmap, accs[, retire[, pool[, device]]]) "
     "-> batch-lane class id; retire(n) fires after each batch's outputs "
     "land; pool routes ready tasks through the bound scheduler plane; "
     "device=1 surfaces ready tasks onto the ptdev lane once dev-bound"},
    {"insert_many", engine_insert_many, METH_O,
     "insert_many(specs) -> count; links the whole batch under one GIL "
     "drop (count-then-activate per task)"},
    {"drain_ready", engine_drain_ready, METH_VARARGS,
     "drain_ready(max_batch=256, budget=4096, wid=0) -> (n_executed, "
     "surfaced); runs ready batch-lane tasks via per-class batched "
     "callbacks (wid = scheduler-plane hot-queue affinity)"},
    {"sched_bind", engine_sched_bind, METH_O,
     "sched_bind(plane_capsule): attach the shared scheduler plane "
     "(see native/src/ptsched.h); idempotent for the same plane"},
    {"sched_bound", engine_sched_bound, METH_NOARGS,
     "True when a scheduler plane is attached"},
    {"slot_set", engine_slot_set, METH_VARARGS,
     "slot_set(tile_id, payload): seed/refresh a tile's payload slot"},
    {"slot_get", engine_slot_get, METH_O,
     "slot_get(tile_id) -> payload or None"},
    {"slot_sync", engine_slot_sync, METH_O,
     "slot_sync(tile_id) -> (payload, writes-since-last-sync); resets the "
     "write counter"},
    {"release_pool", engine_release_pool, METH_VARARGS,
     "release_pool(tile_ids, class_ids): drop a completed pool's slot "
     "payloads and class callbacks"},
    {"successors", engine_successors, METH_O,
     "successors(task_id) -> tuple of successor ids (query BEFORE "
     "complete(); instrumentation mirror for PINS consumers)"},
    {"trace_enable", engine_trace_enable, METH_VARARGS,
     "trace_enable(nrings=16, capacity=65536) -> (nrings, cap): arm the "
     "in-lane event rings (idempotent; see ptrace_ring.h)"},
    {"trace_disable", engine_trace_disable, METH_NOARGS,
     "stop recording (rings and drop counters are kept)"},
    {"trace_drain", engine_trace_drain, METH_NOARGS,
     "trace_drain() -> [(ring_id, packed_events_bytes)]; event layout "
     "'<qqII' = (t_ns, id, key, flags)"},
    {"trace_dropped", engine_trace_dropped, METH_NOARGS,
     "cumulative events lost to ring overflow (never reset)"},
    {"monotonic_ns", engine_monotonic_ns, METH_NOARGS,
     "the trace clock (steady_clock ns) — for epoch calibration"},
    {"hist_enable", engine_hist_enable, METH_NOARGS,
     "arm the batch-lane latency histograms (exec_ns amortized per "
     "(class,batch), ready_wait_ns push->pop; see pthist.h)"},
    {"hist_disable", engine_hist_disable, METH_NOARGS,
     "stop recording (buckets are kept)"},
    {"hist_snapshot", engine_hist_snapshot, METH_NOARGS,
     "{name: (count, sum_ns, buckets_bytes)} — buckets pack '<496Q'"},
    {"deps_remaining", engine_deps_remaining, METH_O,
     "deps_remaining(task_id) -> int"},
    {"pending", engine_pending, METH_NOARGS,
     "live (incomplete) task count"},
    {"ready_count", engine_ready_count, METH_NOARGS,
     "ready batch-lane tasks awaiting drain"},
    {"batch_executed", engine_batch_executed, METH_NOARGS,
     "total batch-lane tasks executed by drain_ready"},
    {"sizes", engine_sizes, METH_NOARGS,
     "(total tasks ever, total tiles) — memory diagnostics"},
    {"ingest", engine_ingest, METH_O,
     "ingest(tid): one remote dep-release arrived for task tid"},
    {"ingest_capsule", engine_ingest_capsule, METH_NOARGS,
     "PyCapsule(PtCommIngestVtbl) for Comm.register_pool (GIL-free ingest)"},
    {"comm_stats", engine_comm_stats, METH_NOARGS,
     "{acts_rx, ingest_bad, rsurf_pending}"},
    {"dev_bind", engine_dev_bind, METH_VARARGS,
     "dev_bind(submit_capsule, dev_pool): ready tasks of device-marked "
     "classes surface onto the ptdev lane (bind before inserting them)"},
    {"dev_retire_capsule", engine_dev_retire_capsule, METH_NOARGS,
     "PyCapsule(PtDevRetireVtbl) for Lane.bind_pool (GIL-free retirement)"},
    {"dev_retire", engine_dev_retire, METH_O,
     "dev_retire(tid): one device task completed; run its release walk"},
    {"dev_stats", engine_dev_stats, METH_NOARGS,
     "{dev_tx, dev_done, dev_bad}"},
    {nullptr, nullptr, 0, nullptr}};

// ----------------------------------------------------- insert fast path

// Interned attribute names + the small-int singletons the fast path
// compares against, created once at module init: the per-call
// GetAttrString/PyLong_AsLong round-trips were ~40% of try_buffer's cost
// at the measured ~600ns/call.
PyObject *s_nid = nullptr;      // "nid"
PyObject *s_zero = nullptr;     // int 0   (default priority)
PyObject *s_devall = nullptr;   // int 255 (DEV_ALL)

// try_buffer(fstate, fn, args, priority, where, jit, batch) -> int
//
// The MODULE-LEVEL insert_task fast path: validates one insert call
// against the pool's one-entry fast cache and appends its batch spec to
// the insert buffer — the ~30 interpreter bytecodes the Python fast path
// would spend per insert collapse into one C call (METH_FASTCALL: no
// argument tuple is ever materialized). Touches NO engine state (the
// buffer is a plain Python list; append is GIL-atomic), so it is a free
// function, not a method.
//
//   fstate: (fn, jit, batch, kinds, cls, buf, flush_n, tile_type)
//       kinds: bare acc int for the single-flow shape, else a tuple with
//       one entry per arg — the acc int for flow positions, None for
//       by-value positions. tile_type: the DTDTile class (exact match).
//   returns 0 = take the slow path, 1 = buffered,
//           2 = buffered and the flush threshold was reached
PyObject *ptdtd_try_buffer(PyObject *, PyObject *const *fc,
                           Py_ssize_t nfc) {
    if (nfc != 7) {
        PyErr_SetString(PyExc_TypeError, "try_buffer takes 7 arguments");
        return nullptr;
    }
    PyObject *fstate = fc[0], *fn = fc[1], *args = fc[2], *priority = fc[3],
             *where = fc[4], *jit = fc[5], *batch = fc[6];
    if (!PyTuple_Check(fstate) || PyTuple_GET_SIZE(fstate) != 8 ||
        !PyTuple_Check(args))
        return PyLong_FromLong(0);
    // gate: same fn object, same jit/batch flags (canonical bools compare
    // by identity), priority 0, no device restriction. Small ints are
    // singletons in CPython, so the common literals hit the pointer
    // compare; anything else takes the boxed-value check once.
    if (PyTuple_GET_ITEM(fstate, 0) != fn ||
        PyTuple_GET_ITEM(fstate, 1) != jit ||
        PyTuple_GET_ITEM(fstate, 2) != batch)
        return PyLong_FromLong(0);
    if (priority != s_zero &&
        (!PyLong_CheckExact(priority) || PyLong_AsLong(priority) != 0)) {
        if (PyErr_Occurred()) PyErr_Clear();
        return PyLong_FromLong(0);
    }
    if (where != s_devall &&
        (!PyLong_CheckExact(where) || PyLong_AsLong(where) != 0xFF)) {
        if (PyErr_Occurred()) PyErr_Clear();
        return PyLong_FromLong(0);
    }
    PyObject *kinds = PyTuple_GET_ITEM(fstate, 3);
    PyObject *cls = PyTuple_GET_ITEM(fstate, 4);
    PyObject *buf = PyTuple_GET_ITEM(fstate, 5);
    PyObject *flushn_o = PyTuple_GET_ITEM(fstate, 6);
    PyObject *tile_type = PyTuple_GET_ITEM(fstate, 7);
    if (!PyList_Check(buf)) return PyLong_FromLong(0);
    PyObject *spec = nullptr;
    if (PyLong_CheckExact(kinds)) {
        // single-flow shape: args == ((tile, acc),) with acc == kinds
        if (PyTuple_GET_SIZE(args) != 1) return PyLong_FromLong(0);
        PyObject *a = PyTuple_GET_ITEM(args, 0);
        if (!PyTuple_CheckExact(a) || PyTuple_GET_SIZE(a) != 2)
            return PyLong_FromLong(0);
        PyObject *acc = PyTuple_GET_ITEM(a, 1);
        int eq = PyObject_RichCompareBool(acc, kinds, Py_EQ);
        if (eq < 0) { PyErr_Clear(); return PyLong_FromLong(0); }
        if (!eq) return PyLong_FromLong(0);
        PyObject *tile = PyTuple_GET_ITEM(a, 0);
        if ((PyObject *)Py_TYPE(tile) != tile_type)
            return PyLong_FromLong(0);
        PyObject *nid = PyObject_GetAttr(tile, s_nid);
        if (!nid) { PyErr_Clear(); return PyLong_FromLong(0); }
        if (nid == Py_None) {    // first native touch: slow path seeds it
            Py_DECREF(nid);
            return PyLong_FromLong(0);
        }
        spec = PyTuple_New(4);
        if (!spec) { Py_DECREF(nid); return nullptr; }
        Py_INCREF(cls);
        Py_INCREF(Py_None);
        Py_INCREF(kinds);
        PyTuple_SET_ITEM(spec, 0, cls);
        PyTuple_SET_ITEM(spec, 1, Py_None);
        PyTuple_SET_ITEM(spec, 2, nid);
        PyTuple_SET_ITEM(spec, 3, kinds);
    } else {
        // general shape: walk the kinds pattern
        if (!PyTuple_CheckExact(kinds) ||
            PyTuple_GET_SIZE(args) != PyTuple_GET_SIZE(kinds))
            return PyLong_FromLong(0);
        Py_ssize_t na = PyTuple_GET_SIZE(kinds);
        PyObject *vals = nullptr;   // lazily built list of by-value args
        std::vector<PyObject *> flows;   // borrowed (nid, acc) pairs...
        std::vector<PyObject *> owned;   // nid refs to release on bail
        bool ok = true;
        for (Py_ssize_t i = 0; i < na && ok; i++) {
            PyObject *k = PyTuple_GET_ITEM(kinds, i);
            PyObject *a = PyTuple_GET_ITEM(args, i);
            if (k == Py_None) {
                // by-value position: a flow-shaped arg changes the spec
                if ((PyObject *)Py_TYPE(a) == tile_type) { ok = false; break; }
                if (PyTuple_CheckExact(a) && PyTuple_GET_SIZE(a) == 2 &&
                    (PyObject *)Py_TYPE(PyTuple_GET_ITEM(a, 0)) ==
                        tile_type) { ok = false; break; }
                if (!vals) {
                    vals = PyList_New(0);
                    if (!vals) { ok = false; break; }
                }
                if (PyList_Append(vals, a) < 0) { ok = false; break; }
            } else {
                if (!PyTuple_CheckExact(a) || PyTuple_GET_SIZE(a) != 2) {
                    ok = false; break;
                }
                int eq = PyObject_RichCompareBool(PyTuple_GET_ITEM(a, 1),
                                                  k, Py_EQ);
                if (eq <= 0) { ok = false; break; }
                PyObject *tile = PyTuple_GET_ITEM(a, 0);
                if ((PyObject *)Py_TYPE(tile) != tile_type) {
                    ok = false; break;
                }
                PyObject *nid = PyObject_GetAttr(tile, s_nid);
                if (!nid || nid == Py_None) {
                    if (!nid) PyErr_Clear();
                    Py_XDECREF(nid); ok = false; break;
                }
                owned.push_back(nid);
                flows.push_back(nid);
                flows.push_back(k);
            }
        }
        if (!ok) {
            if (PyErr_Occurred()) PyErr_Clear();
            for (PyObject *o : owned) Py_DECREF(o);
            Py_XDECREF(vals);
            return PyLong_FromLong(0);
        }
        spec = PyTuple_New(2 + (Py_ssize_t)flows.size());
        if (!spec) {
            for (PyObject *o : owned) Py_DECREF(o);
            Py_XDECREF(vals);
            return nullptr;
        }
        Py_INCREF(cls);
        PyTuple_SET_ITEM(spec, 0, cls);
        if (vals) {
            PyObject *vt = PyList_AsTuple(vals);
            Py_DECREF(vals);
            if (!vt) {
                for (PyObject *o : owned) Py_DECREF(o);
                Py_DECREF(spec);
                return nullptr;
            }
            PyTuple_SET_ITEM(spec, 1, vt);
        } else {
            Py_INCREF(Py_None);
            PyTuple_SET_ITEM(spec, 1, Py_None);
        }
        for (size_t i = 0; i < flows.size(); i += 2) {
            PyTuple_SET_ITEM(spec, 2 + (Py_ssize_t)i, flows[i]); // owned nid
            Py_INCREF(flows[i + 1]);
            PyTuple_SET_ITEM(spec, 3 + (Py_ssize_t)i, flows[i + 1]);
        }
    }
    int rc = PyList_Append(buf, spec);
    Py_DECREF(spec);
    if (rc < 0) return nullptr;
    long flushn = PyLong_AsLong(flushn_o);
    if (flushn > 0 && PyList_GET_SIZE(buf) >= flushn)
        return PyLong_FromLong(2);
    return PyLong_FromLong(1);
}

PyMethodDef ptdtd_functions[] = {
    {"try_buffer",
     reinterpret_cast<PyCFunction>(
         reinterpret_cast<void (*)(void)>(ptdtd_try_buffer)),
     METH_FASTCALL,
     "insert_task fast path: validate one call against the pool's fast "
     "cache and append its batch spec (0=slow path, 1=buffered, "
     "2=buffered+flush)"},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject EngineType = [] {
    PyTypeObject t = {PyVarObject_HEAD_INIT(nullptr, 0)};
    t.tp_name = "parsec_tpu._ptdtd.Engine";
    t.tp_basicsize = sizeof(Engine);
    t.tp_flags = Py_TPFLAGS_DEFAULT;
    t.tp_doc = "single-rank DTD dependency engine (native hot path)";
    t.tp_new = engine_new;
    t.tp_dealloc = engine_dealloc;
    t.tp_methods = engine_methods;
    return t;
}();

PyModuleDef ptdtd_module = {
    PyModuleDef_HEAD_INIT, "_ptdtd",
    "native DTD dependency engine (see native/src/ptdtd.cpp)", -1,
    ptdtd_functions, nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__ptdtd(void) {
    if (PyType_Ready(&EngineType) < 0) return nullptr;
    s_nid = PyUnicode_InternFromString("nid");
    s_zero = PyLong_FromLong(0);
    s_devall = PyLong_FromLong(0xFF);
    if (!s_nid || !s_zero || !s_devall) return nullptr;
    PyObject *m = PyModule_Create(&ptdtd_module);
    if (!m) return nullptr;
    Py_INCREF(&EngineType);
    if (PyModule_AddObject(m, "Engine",
                           reinterpret_cast<PyObject *>(&EngineType)) < 0) {
        Py_DECREF(&EngineType);
        Py_DECREF(m);
        return nullptr;
    }
    if (PyModule_AddIntConstant(m, "EV_LINK", EV_LINK) < 0 ||
        PyModule_AddIntConstant(m, "EV_EXEC", EV_EXEC) < 0 ||
        PyModule_AddIntConstant(m, "EV_TASK", EV_TASK) < 0 ||
        PyModule_AddIntConstant(m, "HIST_BUCKETS", pthist::NBUCKETS) < 0 ||
        PyModule_AddIntConstant(m, "HIST_SUB_BITS", pthist::SUB_BITS) < 0) {
        Py_DECREF(m);
        return nullptr;
    }
    return m;
}
