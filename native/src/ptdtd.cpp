// parsec_tpu._ptdtd — the DTD dependency engine as a CPython extension.
//
// Stands where the reference's C insert path stands
// (parsec/interfaces/dtd/insert_function.c:3617 parsec_dtd_insert_task ->
// parsec_dtd_set_params_of_task insert_function.c:2896 and the release walk
// parsec_dtd_ordering_correctly, insert_function_internal.h:277): runtime
// dependency discovery over per-tile last-writer/reader chains, the
// insertion-guard count-then-activate protocol, and the successor release
// that collects newly-ready tasks.
//
// Why a CPython extension and not ctypes: this is called ONCE PER TASK on
// the insert and completion hot paths; a ctypes boundary costs ~2 us while
// a C-extension method call costs ~0.2 us (measured in this container —
// see parsec_tpu/native.py's docstring for the ctypes numbers).
//
// Scope: the SINGLE-RANK engine. Distributed inserts, the replay auditor,
// and remote version bookkeeping stay in the Python engine (dsl/dtd.py
// _link_tile) — they are protocol-bound, not insert-rate-bound. The Python
// side gates which engine a taskpool uses (DTDTaskpool._native_engine).
//
// Concurrency: every entry point runs under the GIL (worker threads call
// complete() from Python), which serializes access; no internal locks.
// Task/tile records live in growing arrays; ids are indices and are never
// recycled (a completed task id may persist as a tile's last_writer).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <new>
#include <vector>

namespace {

constexpr int32_t ACC_READ = 0x1;    // mirrors dsl/dtd.py READ
constexpr int32_t ACC_WRITE = 0x2;   // mirrors dsl/dtd.py WRITE

struct TaskRec {
    int32_t deps_remaining = 1;   // the insertion-in-progress guard
    bool completed = false;
    uint32_t stamp = 0;           // pred-dedup visit stamp
    std::vector<int64_t> succs;
};

struct TileRec {
    int64_t last_writer = -1;
    int32_t compact_at = 32;      // reader-list compaction watermark
    std::vector<int64_t> readers;
};

struct Engine {
    PyObject_HEAD
    std::vector<TaskRec> *tasks;
    std::vector<TileRec> *tiles;
    uint32_t stamp;
    int64_t live;                 // inserted - completed
};

PyObject *engine_new(PyTypeObject *type, PyObject *, PyObject *) {
    Engine *self = reinterpret_cast<Engine *>(type->tp_alloc(type, 0));
    if (!self) return nullptr;
    self->tasks = new (std::nothrow) std::vector<TaskRec>();
    self->tiles = new (std::nothrow) std::vector<TileRec>();
    self->stamp = 0;
    self->live = 0;
    if (!self->tasks || !self->tiles) {
        Py_DECREF(self);
        PyErr_NoMemory();
        return nullptr;
    }
    return reinterpret_cast<PyObject *>(self);
}

void engine_dealloc(PyObject *obj) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    delete self->tasks;
    delete self->tiles;
    Py_TYPE(obj)->tp_free(obj);
}

// tile() -> int : register a new tile chain
PyObject *engine_tile(PyObject *obj, PyObject *) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    self->tiles->emplace_back();
    return PyLong_FromSsize_t((Py_ssize_t)self->tiles->size() - 1);
}

// insert(tile_ids: list|tuple[int], accs: list|tuple[int])
//   -> (task_id, deps_remaining)   — the insertion guard is STILL HELD
//
// Replicates dsl/dtd.py _link_tile single-rank semantics exactly:
//   READ (or access without WRITE): RAW pred on the live last writer;
//     the task joins the tile's reader list (amortized compaction of
//     completed readers past the doubling watermark).
//   WRITE: WAR preds on live readers, WAW pred on the live last writer;
//     the tile chain then points at this task and the reader list resets.
// Preds are deduplicated (visit stamps) and self-edges skipped; each live
// pred gains a successor edge and bumps this task's dep count.
//
// The insertion guard (count starts at 1) is NOT dropped here: the caller
// must publish its id->task bookkeeping and then call activate(task_id),
// which drops the guard — the count-then-activate protocol of
// parsec_dtd_schedule_task_if_ready (insert_function.c:2963). Dropping
// the guard inside insert() would let a fast predecessor completing on a
// worker thread surface this id from complete() BEFORE the inserting
// thread has mapped it (the round-5 activation race, ADVICE.md).
PyObject *engine_insert(PyObject *obj, PyObject *args) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    PyObject *tile_ids, *accs;
    if (!PyArg_ParseTuple(args, "OO", &tile_ids, &accs))
        return nullptr;
    // lists are what the hot caller builds; accept tuples too
    const bool til = PyList_Check(tile_ids), acl = PyList_Check(accs);
    if ((!til && !PyTuple_Check(tile_ids)) ||
        (!acl && !PyTuple_Check(accs))) {
        PyErr_SetString(PyExc_TypeError, "tile_ids/accs: list or tuple");
        return nullptr;
    }
    Py_ssize_t nflows = til ? PyList_GET_SIZE(tile_ids)
                            : PyTuple_GET_SIZE(tile_ids);
    if ((acl ? PyList_GET_SIZE(accs) : PyTuple_GET_SIZE(accs)) != nflows) {
        PyErr_SetString(PyExc_ValueError, "tile_ids/accs length mismatch");
        return nullptr;
    }

    std::vector<TaskRec> &tasks = *self->tasks;
    std::vector<TileRec> &tiles = *self->tiles;

    // validate EVERYTHING before mutating any chain state: a mid-loop
    // failure after linking flow 0 would leave successor edges (and
    // possibly tile.last_writer) pointing at a popped — soon reused — id
    constexpr Py_ssize_t PT_FLOWS_MAX = 64;
    if (nflows > PT_FLOWS_MAX) {
        PyErr_SetString(PyExc_ValueError, "too many flows (max 64)");
        return nullptr;
    }
    int64_t tixs[PT_FLOWS_MAX];
    long laccs[PT_FLOWS_MAX];
    for (Py_ssize_t i = 0; i < nflows; i++) {
        tixs[i] = PyLong_AsLongLong(
            til ? PyList_GET_ITEM(tile_ids, i)
                : PyTuple_GET_ITEM(tile_ids, i));
        laccs[i] = PyLong_AsLong(acl ? PyList_GET_ITEM(accs, i)
                                     : PyTuple_GET_ITEM(accs, i));
        if (!PyErr_Occurred() &&
            (tixs[i] < 0 || (size_t)tixs[i] >= tiles.size()))
            PyErr_SetString(PyExc_IndexError, "bad tile id");
        if (PyErr_Occurred()) return nullptr;
    }

    const int64_t tid = (int64_t)tasks.size();
    tasks.emplace_back();
    self->live++;
    // note: emplace may reallocate; take references AFTER any growth
    if (++self->stamp == 0) {     // stamp wrapped: clear all (rare)
        for (auto &t : tasks) t.stamp = 0;
        self->stamp = 1;
    }
    const uint32_t stamp = self->stamp;
    int32_t new_deps = 0;

    for (Py_ssize_t i = 0; i < nflows; i++) {
        int64_t tix = tixs[i];
        long acc = laccs[i];
        TileRec &tile = tiles[(size_t)tix];
        const bool is_read = (acc & ACC_READ) || !(acc & ACC_WRITE);
        if (is_read) {
            int64_t lw = tile.last_writer;
            if (lw >= 0 && !tasks[(size_t)lw].completed &&
                lw != tid && tasks[(size_t)lw].stamp != stamp) {
                tasks[(size_t)lw].stamp = stamp;
                tasks[(size_t)lw].succs.push_back(tid);
                new_deps++;
            }
            if (!(acc & ACC_WRITE)) {   // pure READ joins the reader list
                if ((int32_t)tile.readers.size() >= tile.compact_at) {
                    size_t w = 0;       // prune completed readers in place
                    for (size_t r = 0; r < tile.readers.size(); r++)
                        if (!tasks[(size_t)tile.readers[r]].completed)
                            tile.readers[w++] = tile.readers[r];
                    tile.readers.resize(w);
                    int32_t dbl = 2 * (int32_t)(w + 1);
                    tile.compact_at = dbl > 32 ? dbl : 32;
                }
                tile.readers.push_back(tid);
            }
        }
        if (acc & ACC_WRITE) {
            if (acc & ACC_READ) {       // RW also joined RAW above; reader
                // list membership is superseded by becoming the writer
            }
            for (int64_t r : tile.readers) {
                if (r == tid) continue;
                TaskRec &rr = tasks[(size_t)r];
                if (!rr.completed && rr.stamp != stamp) {
                    rr.stamp = stamp;
                    rr.succs.push_back(tid);
                    new_deps++;
                }
            }
            int64_t lw = tile.last_writer;
            if (lw >= 0 && lw != tid) {
                TaskRec &lwr = tasks[(size_t)lw];
                if (!lwr.completed && lwr.stamp != stamp) {
                    lwr.stamp = stamp;
                    lwr.succs.push_back(tid);
                    new_deps++;
                }
            }
            tile.last_writer = tid;
            tile.readers.clear();
            tile.compact_at = 32;
        }
    }

    TaskRec &rec = tasks[(size_t)tid];
    rec.deps_remaining += new_deps;                  // guard still held
    return Py_BuildValue("(Li)", (long long)tid, (int)rec.deps_remaining);
}

// activate(task_id) -> deps_remaining after dropping the insertion guard
// (0 == ready NOW and the caller owns scheduling it; a concurrent
// complete() can never have reported it). Call exactly once per insert,
// AFTER the id->task map is populated.
PyObject *engine_activate(PyObject *obj, PyObject *arg) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    int64_t tid = PyLong_AsLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    std::vector<TaskRec> &tasks = *self->tasks;
    if (tid < 0 || (size_t)tid >= tasks.size()) {
        PyErr_SetString(PyExc_IndexError, "bad task id");
        return nullptr;
    }
    TaskRec &rec = tasks[(size_t)tid];
    if (rec.completed) {
        PyErr_SetString(PyExc_RuntimeError, "activate after completion");
        return nullptr;
    }
    return PyLong_FromLong(--rec.deps_remaining);
}

// complete(task_id) -> tuple of newly-ready task ids (often empty)
PyObject *engine_complete(PyObject *obj, PyObject *arg) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    int64_t tid = PyLong_AsLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    std::vector<TaskRec> &tasks = *self->tasks;
    if (tid < 0 || (size_t)tid >= tasks.size()) {
        PyErr_SetString(PyExc_IndexError, "bad task id");
        return nullptr;
    }
    TaskRec &rec = tasks[(size_t)tid];
    if (rec.completed) {
        PyErr_SetString(PyExc_RuntimeError, "task completed twice");
        return nullptr;
    }
    rec.completed = true;
    self->live--;
    // move out the successor list so the record sheds its heap storage
    std::vector<int64_t> succs;
    succs.swap(rec.succs);
    int64_t ready[64];
    size_t nready = 0;
    PyObject *out = nullptr;
    for (int64_t s : succs) {
        TaskRec &sr = tasks[(size_t)s];
        if (--sr.deps_remaining == 0) {
            if (nready < 64) {
                ready[nready++] = s;
            } else {
                // very wide release: spill into the tuple path
                if (!out) {
                    out = PyList_New(0);
                    if (!out) return nullptr;
                    for (size_t i = 0; i < nready; i++) {
                        PyObject *v = PyLong_FromLongLong(ready[i]);
                        if (!v || PyList_Append(out, v) < 0) {
                            Py_XDECREF(v); Py_DECREF(out); return nullptr;
                        }
                        Py_DECREF(v);
                    }
                }
                PyObject *v = PyLong_FromLongLong(s);
                if (!v || PyList_Append(out, v) < 0) {
                    Py_XDECREF(v); Py_DECREF(out); return nullptr;
                }
                Py_DECREF(v);
            }
        }
    }
    if (out) {
        PyObject *tup = PyList_AsTuple(out);
        Py_DECREF(out);
        return tup;
    }
    PyObject *tup = PyTuple_New((Py_ssize_t)nready);
    if (!tup) return nullptr;
    for (size_t i = 0; i < nready; i++) {
        PyObject *v = PyLong_FromLongLong(ready[i]);
        if (!v) { Py_DECREF(tup); return nullptr; }
        PyTuple_SET_ITEM(tup, (Py_ssize_t)i, v);
    }
    return tup;
}

// deps_remaining(task_id) -> int  (diagnostics / paranoid checks)
PyObject *engine_deps_remaining(PyObject *obj, PyObject *arg) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    int64_t tid = PyLong_AsLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    if (tid < 0 || (size_t)tid >= self->tasks->size()) {
        PyErr_SetString(PyExc_IndexError, "bad task id");
        return nullptr;
    }
    return PyLong_FromLong((*self->tasks)[(size_t)tid].deps_remaining);
}

PyObject *engine_pending(PyObject *obj, PyObject *) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    return PyLong_FromLongLong(self->live);
}

PyObject *engine_sizes(PyObject *obj, PyObject *) {
    Engine *self = reinterpret_cast<Engine *>(obj);
    return Py_BuildValue("(nn)", (Py_ssize_t)self->tasks->size(),
                         (Py_ssize_t)self->tiles->size());
}

PyMethodDef engine_methods[] = {
    {"tile", engine_tile, METH_NOARGS,
     "register a tile chain; returns its id"},
    {"insert", engine_insert, METH_VARARGS,
     "insert(tile_ids, accs) -> (task_id, deps_remaining); the insertion "
     "guard stays held until activate(task_id)"},
    {"activate", engine_activate, METH_O,
     "drop the insertion guard; returns deps remaining (0 = ready now)"},
    {"complete", engine_complete, METH_O,
     "complete(task_id) -> tuple of newly-ready task ids"},
    {"deps_remaining", engine_deps_remaining, METH_O,
     "deps_remaining(task_id) -> int"},
    {"pending", engine_pending, METH_NOARGS,
     "live (incomplete) task count"},
    {"sizes", engine_sizes, METH_NOARGS,
     "(total tasks ever, total tiles) — memory diagnostics"},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject EngineType = [] {
    PyTypeObject t = {PyVarObject_HEAD_INIT(nullptr, 0)};
    t.tp_name = "parsec_tpu._ptdtd.Engine";
    t.tp_basicsize = sizeof(Engine);
    t.tp_flags = Py_TPFLAGS_DEFAULT;
    t.tp_doc = "single-rank DTD dependency engine (native hot path)";
    t.tp_new = engine_new;
    t.tp_dealloc = engine_dealloc;
    t.tp_methods = engine_methods;
    return t;
}();

PyModuleDef ptdtd_module = {
    PyModuleDef_HEAD_INIT, "_ptdtd",
    "native DTD dependency engine (see native/src/ptdtd.cpp)", -1,
    nullptr, nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__ptdtd(void) {
    if (PyType_Ready(&EngineType) < 0) return nullptr;
    PyObject *m = PyModule_Create(&ptdtd_module);
    if (!m) return nullptr;
    Py_INCREF(&EngineType);
    if (PyModule_AddObject(m, "Engine",
                           reinterpret_cast<PyObject *>(&EngineType)) < 0) {
        Py_DECREF(&EngineType);
        Py_DECREF(m);
        return nullptr;
    }
    return m;
}
