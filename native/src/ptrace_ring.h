// In-lane event tracing for the native execution engines (ptexec, ptdtd).
//
// The observability half of the lane contract (ISSUE 5): the reference
// instruments its ACTUAL hot path (parsec/profiling.c per-ES buffers,
// PINS callback chains); once our task FSMs moved into C, enabling the
// Python profilers silently ejected pools back onto a ~100x-slower
// interpreted machine — the recorded trace described a machine that never
// runs in production. These rings record events INSIDE the lane instead:
//
//  * per-WORKER fixed-capacity rings: one engine call (Graph.run /
//    Engine.drain_ready / Engine.insert_many) claims a ring for its
//    duration, so each ring has exactly ONE producer at a time and the
//    drain (Python, GIL held) is the single consumer — a classic SPSC
//    hand-off on two atomic cursors, no locks on the record path;
//  * events are (key, id, flags, monotonic-ns) — 24 bytes, one relaxed
//    store each; the whole facility is gated by a single relaxed-atomic
//    enabled flag (a null `Writer.st` — one predictable branch per event
//    site when tracing is off, zero allocations);
//  * overflow NEVER blocks the lane: a full ring drops the event and
//    bumps the ring's drop counter (drop accounting is part of the trace
//    contract — `trace.events_dropped` in the counter registry);
//  * the drain hands each ring's pending span to Python as one packed
//    bytes object (struct layout "<qqII": t_ns, id, key, flags) which
//    utils/native_trace.py lands into the PBP dictionary/streams.
//
// Timestamps are steady_clock ns — CLOCK_MONOTONIC on glibc, the same
// clock CPython's time.perf_counter() reads on Linux; the Python bridge
// still calibrates an offset at attach so the epoch assumption is not
// load-bearing.

#ifndef PARSEC_TPU_PTRACE_RING_H
#define PARSEC_TPU_PTRACE_RING_H

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <new>

namespace ptrace_ring {

constexpr uint32_t FLAG_START = 0x1;   // mirror utils/trace.py EVENT_FLAG_*
constexpr uint32_t FLAG_END = 0x2;
constexpr uint32_t FLAG_POINT = 0x4;

constexpr int MAX_RINGS = 64;
constexpr int DEFAULT_RINGS = 16;
constexpr uint32_t DEFAULT_CAP = 1 << 16;

inline int64_t now_ns() {
    return (int64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
}

struct Event {          // 24 bytes, packed struct fmt "<qqII"
    int64_t t_ns;
    int64_t id;
    uint32_t key;
    uint32_t flags;
};

struct Ring {
    Event *buf = nullptr;
    uint32_t cap = 0;
    std::atomic<uint64_t> head{0};     // producer cursor (claimed caller)
    std::atomic<uint64_t> tail{0};     // consumer cursor (Python drain)
    std::atomic<uint64_t> dropped{0};  // events lost to overflow (cumulative)
    std::atomic<int> busy{0};          // claimed by a running engine call
};

struct State {
    std::atomic<bool> enabled{false};
    Ring *rings = nullptr;
    int nrings = 0;
    // engine calls that found every ring claimed record nothing; their
    // would-be events count here so the drop accounting stays honest
    std::atomic<uint64_t> unclaimed{0};

    bool enable(int n, uint32_t cap) {
        if (rings) {                   // idempotent: keep the first config
            enabled.store(true, std::memory_order_release);
            return true;
        }
        if (n <= 0) n = DEFAULT_RINGS;
        if (n > MAX_RINGS) n = MAX_RINGS;
        if (cap < 16) cap = 16;
        Ring *r = new (std::nothrow) Ring[(size_t)n];
        if (!r) return false;
        for (int i = 0; i < n; i++) {
            r[i].buf = new (std::nothrow) Event[cap];
            if (!r[i].buf) {
                for (int j = 0; j < i; j++) delete[] r[j].buf;
                delete[] r;
                return false;
            }
            r[i].cap = cap;
        }
        rings = r;
        nrings = n;
        enabled.store(true, std::memory_order_release);
        return true;
    }

    void disable() { enabled.store(false, std::memory_order_release); }

    uint64_t total_dropped() const {
        uint64_t d = unclaimed.load(std::memory_order_relaxed);
        for (int i = 0; i < nrings; i++)
            d += rings[i].dropped.load(std::memory_order_relaxed);
        return d;
    }

    ~State() {
        for (int i = 0; i < nrings; i++) delete[] rings[i].buf;
        delete[] rings;
    }
};

// One engine call's claim on a ring. open() scans for a free ring with a
// CAS (bounded: MAX_RINGS tries). Event sites gate on `st` (null iff
// tracing is off — one predictable branch); with tracing ON but every
// ring claimed, `r` stays null and rec() counts the lost events into
// State::unclaimed so the drop accounting stays honest. Destructor
// releases the claim, so early returns / error paths cannot leak a busy
// ring.
struct Writer {
    Ring *r = nullptr;
    State *st = nullptr;

    void open(State *state) {
        // acquire pairs with enable()'s release store: a worker that sees
        // enabled==true also sees the fully-built rings/nrings (the
        // engines likewise load their State pointer with acquire)
        if (!state || !state->enabled.load(std::memory_order_acquire))
            return;
        for (int i = 0; i < state->nrings; i++) {
            int expect = 0;
            if (state->rings[i].busy.compare_exchange_strong(
                    expect, 1, std::memory_order_acquire)) {
                r = &state->rings[i];
                st = state;
                return;
            }
        }
        st = state;   // all claimed: record() counts into unclaimed
    }

    inline void rec(uint32_t key, int64_t id, uint32_t flags) {
        if (!r) {
            if (st) st->unclaimed.fetch_add(1, std::memory_order_relaxed);
            return;
        }
        uint64_t h = r->head.load(std::memory_order_relaxed);
        uint64_t t = r->tail.load(std::memory_order_acquire);
        if (h - t >= r->cap) {         // full: drop, never block the lane
            r->dropped.fetch_add(1, std::memory_order_relaxed);
            return;
        }
        Event &e = r->buf[h % r->cap];
        e.t_ns = now_ns();
        e.id = id;
        e.key = key;
        e.flags = flags;
        r->head.store(h + 1, std::memory_order_release);
    }

    void close() {
        if (r) {
            r->busy.store(0, std::memory_order_release);
            r = nullptr;
        }
        st = nullptr;
    }

    ~Writer() { close(); }
};

// ------------------------------------------------------------ Python API
// The method bodies shared by both extensions. Each embeds a
// `std::atomic<State *> trace` in its object struct: engine calls run
// with the GIL dropped while trace_enable (GIL held) publishes the
// State, so the pointer itself needs release/acquire ordering.

// trace_enable(nrings=DEFAULT_RINGS, capacity=DEFAULT_CAP) -> (nrings, cap)
inline PyObject *py_trace_enable(std::atomic<State *> &slot, PyObject *args) {
    int nrings = DEFAULT_RINGS;
    unsigned int cap = DEFAULT_CAP;
    if (!PyArg_ParseTuple(args, "|iI", &nrings, &cap)) return nullptr;
    State *st = slot.load(std::memory_order_acquire);
    if (!st) {                         // trace_enable holds the GIL: no
        st = new (std::nothrow) State();   // competing creator
        if (!st) return PyErr_NoMemory();
        if (!st->enable(nrings, (uint32_t)cap)) {
            delete st;
            return PyErr_NoMemory();
        }
        slot.store(st, std::memory_order_release);
    } else if (!st->enable(nrings, (uint32_t)cap)) {
        return PyErr_NoMemory();
    }
    return Py_BuildValue("(iI)", st->nrings,
                         (unsigned int)st->rings[0].cap);
}

inline PyObject *py_trace_disable(State *slot) {
    if (slot) slot->disable();
    Py_RETURN_NONE;
}

// trace_drain() -> list[(ring_id, bytes)] — consumes each ring's pending
// span. Safe against concurrent producers (SPSC cursors); called with the
// GIL held from the Python bridge.
inline PyObject *py_trace_drain(State *slot) {
    PyObject *out = PyList_New(0);
    if (!out || !slot) return out;
    for (int i = 0; i < slot->nrings; i++) {
        Ring &ring = slot->rings[i];
        uint64_t t = ring.tail.load(std::memory_order_relaxed);
        uint64_t h = ring.head.load(std::memory_order_acquire);
        if (h == t) continue;
        uint64_t n = h - t;
        PyObject *b = PyBytes_FromStringAndSize(nullptr,
                                                (Py_ssize_t)(n * sizeof(Event)));
        if (!b) { Py_DECREF(out); return nullptr; }
        char *dst = PyBytes_AS_STRING(b);
        for (uint64_t k = 0; k < n; k++) {
            std::memcpy(dst + k * sizeof(Event),
                        &ring.buf[(t + k) % ring.cap], sizeof(Event));
        }
        ring.tail.store(h, std::memory_order_release);
        PyObject *pair = Py_BuildValue("(iN)", i, b);
        if (!pair || PyList_Append(out, pair) < 0) {
            Py_XDECREF(pair);
            Py_DECREF(out);
            return nullptr;
        }
        Py_DECREF(pair);
    }
    return out;
}

inline PyObject *py_trace_dropped(State *slot) {
    return PyLong_FromUnsignedLongLong(slot ? slot->total_dropped() : 0);
}

}  // namespace ptrace_ring

#endif  // PARSEC_TPU_PTRACE_RING_H
