// Native task-latency histograms for the execution/communication lanes.
//
// The latency half of the lane observability contract (ISSUE 8): the
// ROADMAP's serving north star is "bounded p99 task latency", which no
// counter can express — counters sum, distributions don't. These are
// fixed-bucket log2 histograms in the HdrHistogram style: the bucket
// index is (exponent, sub-bucket) where SUB_BITS sub-buckets split each
// power of two, giving ~12.5% relative resolution at any magnitude with
// a FIXED 496-entry array — no allocation ever happens on the record
// path, and a bump is one relaxed fetch_add (plus two for count/sum).
//
// Gating mirrors ptrace_ring.h: each engine object embeds a
// `std::atomic<State<NH> *>` published with release/acquire; an engine
// call loads it once and event sites pay one predictable null branch
// when histograms are off. The hot execution lanes additionally
// AMORTIZE: per-task execute latency is recorded per batch
// (duration/batch_size bumped batch_size times in one call) and
// ready-queue wait is sampled 1-in-8 by task id, so the armed cost on
// the 10M tasks/s chain walk stays inside the same <2% envelope as the
// PR 5 rings (bench.py `hist_overhead_pct_native` asserts it).
//
// Python (utils/hist.py) mirrors the bucket math, sums snapshots across
// live lanes, and summarizes p50/p99/p999 for the counter registry and
// the /metrics endpoint.

#ifndef PARSEC_TPU_PTHIST_H
#define PARSEC_TPU_PTHIST_H

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <atomic>
#include <cstdint>
#include <cstring>
#include <new>

namespace pthist {

constexpr int SUB_BITS = 3;                 // 8 sub-buckets per power of 2
constexpr int SUBS = 1 << SUB_BITS;
constexpr int NBUCKETS = (64 - SUB_BITS + 1) * SUBS;   // 496

// bucket index for a nanosecond value (negative values clamp to 0).
// u < SUBS maps exactly; above that (exp, top-SUB_BITS-mantissa) — the
// sequence is continuous at u == SUBS (utils/hist.py mirrors this).
inline int bucket_of(int64_t v) {
    uint64_t u = v > 0 ? (uint64_t)v : 0;
    if (u < (uint64_t)SUBS) return (int)u;
    int e = 63 - __builtin_clzll(u);
    int idx = ((e - SUB_BITS + 1) << SUB_BITS) |
              (int)((u >> (e - SUB_BITS)) & (uint64_t)(SUBS - 1));
    return idx < NBUCKETS ? idx : NBUCKETS - 1;
}

struct Hist {
    std::atomic<uint64_t> b[NBUCKETS];
    std::atomic<uint64_t> count;
    std::atomic<uint64_t> sum;      // total ns across all recorded values

    Hist() : count(0), sum(0) {
        for (int i = 0; i < NBUCKETS; i++)
            b[i].store(0, std::memory_order_relaxed);
    }

    // record `n` occurrences of value `v` ns (the batch-amortized form:
    // one call per dispatch batch, n = batch size, v = duration/n)
    inline void add(int64_t v, uint64_t n = 1) {
        b[bucket_of(v)].fetch_add(n, std::memory_order_relaxed);
        count.fetch_add(n, std::memory_order_relaxed);
        sum.fetch_add((uint64_t)(v > 0 ? v : 0) * n,
                      std::memory_order_relaxed);
    }
};

template <int NH>
struct State {
    std::atomic<bool> enabled{true};
    Hist h[NH];
};

// ------------------------------------------------------------ Python API
// Shared method bodies, mirroring ptrace_ring.h's py_trace_* helpers.

// hist_enable(): allocate + publish the zeroed State (idempotent — a
// re-enable after disable keeps the accumulated buckets).
template <int NH>
inline PyObject *py_hist_enable(std::atomic<State<NH> *> &slot) {
    State<NH> *st = slot.load(std::memory_order_acquire);
    if (!st) {                     // GIL held: no competing creator
        st = new (std::nothrow) State<NH>();
        if (!st) return PyErr_NoMemory();
        slot.store(st, std::memory_order_release);
    } else {
        st->enabled.store(true, std::memory_order_release);
    }
    Py_RETURN_NONE;
}

template <int NH>
inline PyObject *py_hist_disable(State<NH> *st) {
    if (st) st->enabled.store(false, std::memory_order_release);
    Py_RETURN_NONE;
}

// hist_snapshot() -> {name: (count, sum_ns, buckets_bytes)} where
// buckets_bytes packs NBUCKETS little-endian u64 counts ("<496Q").
template <int NH>
inline PyObject *py_hist_snapshot(State<NH> *st,
                                  const char *const names[NH]) {
    PyObject *out = PyDict_New();
    if (!out || !st) return out;
    for (int i = 0; i < NH; i++) {
        Hist &h = st->h[i];
        PyObject *b = PyBytes_FromStringAndSize(
            nullptr, (Py_ssize_t)(NBUCKETS * sizeof(uint64_t)));
        if (!b) { Py_DECREF(out); return nullptr; }
        uint64_t *dst = reinterpret_cast<uint64_t *>(PyBytes_AS_STRING(b));
        for (int j = 0; j < NBUCKETS; j++)
            dst[j] = h.b[j].load(std::memory_order_relaxed);
        PyObject *tup = Py_BuildValue(
            "(KKN)",
            (unsigned long long)h.count.load(std::memory_order_relaxed),
            (unsigned long long)h.sum.load(std::memory_order_relaxed), b);
        if (!tup || PyDict_SetItemString(out, names[i], tup) < 0) {
            Py_XDECREF(tup);
            Py_DECREF(out);
            return nullptr;
        }
        Py_DECREF(tup);
    }
    return out;
}

}  // namespace pthist

#endif  // PARSEC_TPU_PTHIST_H
