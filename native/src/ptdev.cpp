// parsec_tpu._ptdev — the native device lane (the fourth extension).
//
// Stands where the reference's GPU device plane stands
// (parsec/mca/device/device_gpu.c: parsec_device_kernel_scheduler:3376,
// the push/exec/pop stream pipeline :3438-3515 and the event-driven
// completion polls :2593) — re-designed for the XLA/PJRT execution model
// the way DiOMP-style portable offload runtimes treat the device plane
// as its OWN subsystem rather than a hook inside the CPU scheduler:
//
//  * a per-device LANE owns a lock-free MPSC pending queue fed STRAIGHT
//    from the GIL-free release sweeps of the execution engines
//    (ptdev_iface.h PtDevSubmitVtbl — a ready device-bodied task never
//    enters the engine's ready vector, it surfaces here; the ptcomm
//    remote-successor surfacing pattern applied to the device plane);
//  * ONE manager thread per lane (the CAS owner/manager model of
//    device_gpu.c:3398-3424, made a real thread) drains the queue, takes
//    the GIL only to issue the JAX dispatch / device_put through a
//    Python callback (XLA dispatch is asynchronous — issuing IS the
//    push+exec phase), then polls completion through a poll callback
//    (jax.Array.is_ready plays cudaEventQuery) and lands each finished
//    task back into its engine through the GIL-FREE retire entry
//    (PtDevRetireVtbl — the ingest_act shape of the comm lane);
//  * the COHERENCY TABLE (CohTable) moves the L1 substrate native: a
//    C-side owner/shared/invalid copy table (the MOESI tracking of
//    data/data.py:transfer_ownership) consulted at stage-in so
//    version-checked transfers are only issued when the device copy is
//    stale, plus zone-heap byte accounting and LRU eviction DECISIONS
//    (parsec_device_data_reserve_space, device_gpu.c:1210). Python owns
//    the payloads and performs the write-backs; C owns residency and
//    eviction policy — the ptexec slot-ownership split.
//
// Concurrency contract: submit() is wait-free from any thread (Treiber
// push + counter); the manager thread is the only consumer. The pool
// table and lifecycle are guarded by `mu`; the manager NEVER holds `mu`
// while acquiring the GIL (bind/unbind take the GIL first, then mu — one
// global order, no inversion). stop() releases the GIL around the join
// so a manager blocked in PyGILState_Ensure can finish its iteration.
//
// Overlap accounting: a dispatch issued while earlier work is still in
// flight means the new batch's H2D transfers overlap the in-flight
// compute — counted per batch (overlap_hits / dispatch_batches is the
// bench's device_overlap_pct_native).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <list>
#include <mutex>
#include <new>
#include <thread>
#include <unordered_map>
#include <vector>

#include "ptdev_iface.h"
#include "ptrace_ring.h"

namespace {

// in-lane trace event keys (registered in the PBP dictionary by
// utils/native_trace.py under "ptdev")
constexpr uint32_t EV_DEV_DISPATCH = 1;  // interval per dispatch batch
constexpr uint32_t EV_DEV_RETIRE = 2;    // point per retired task

// ---------------------------------------------------------------------------
// CohTable — C-side coherency + residency table (one per device)
// ---------------------------------------------------------------------------

// coherency states mirror parsec_tpu/data/data.py (ref: parsec/data.h:28)
constexpr uint8_t COH_INVALID = 0;
constexpr uint8_t COH_OWNED = 1;
constexpr uint8_t COH_SHARED = 3;

struct CohEntry {
    uint32_t version = 0;
    uint8_t state = COH_INVALID;
    int32_t pins = 0;               // readers guard (device_gpu.c:1210)
    int64_t nbytes = 0;
    std::list<uint64_t>::iterator lru_it;
};

struct CohTable {
    PyObject_HEAD
    std::mutex *mu;
    std::unordered_map<uint64_t, CohEntry> *map;
    std::list<uint64_t> *lru;       // front = LRU victim, back = MRU
    int64_t budget;
    int64_t resident;
    int64_t hwm;
    int64_t evictions;
    int64_t pinned_skips;
    int64_t hits;                   // stage-in version checks that matched
    int64_t misses;                 // stage-ins that needed a transfer
    int64_t stage_in_bytes;
    int64_t stage_out_bytes;        // write-backs Python reported
};

// mu held. Evict LRU unpinned entries until `need` bytes fit (or only
// pinned entries remain — then stop, XLA's allocator is the backstop,
// exactly the Python _reserve discipline). Victims append (key, owned).
// `exclude` (with has_exclude) protects the key currently being
// re-staged: evicting it would both under-account the reserve (its old
// bytes were already subtracted from `need`) and hand Python a spurious
// victim for the very copy it is refreshing.
void coh_make_room_locked(CohTable *self, int64_t need,
                          std::vector<std::pair<uint64_t, int>> &victims,
                          bool has_exclude = false, uint64_t exclude = 0) {
    auto it = self->lru->begin();
    while (self->resident + need > self->budget && it != self->lru->end()) {
        uint64_t key = *it;
        if (has_exclude && key == exclude) {
            ++it;
            continue;
        }
        CohEntry &e = (*self->map)[key];
        if (e.pins > 0) {
            self->pinned_skips++;
            ++it;
            continue;
        }
        victims.emplace_back(key, e.state == COH_OWNED ? 1 : 0);
        self->resident -= e.nbytes;
        self->evictions++;
        it = self->lru->erase(it);
        self->map->erase(key);
    }
}

PyObject *coh_victims_py(const std::vector<std::pair<uint64_t, int>> &v) {
    PyObject *out = PyList_New((Py_ssize_t)v.size());
    if (!out) return nullptr;
    for (size_t i = 0; i < v.size(); i++) {
        PyObject *pair = Py_BuildValue("(Ki)", (unsigned long long)v[i].first,
                                       v[i].second);
        if (!pair) { Py_DECREF(out); return nullptr; }
        PyList_SET_ITEM(out, (Py_ssize_t)i, pair);
    }
    return out;
}

PyObject *coh_new(PyTypeObject *type, PyObject *args, PyObject *) {
    long long budget = 0;
    if (!PyArg_ParseTuple(args, "L", &budget)) return nullptr;
    if (budget <= 0) {
        PyErr_SetString(PyExc_ValueError, "budget must be positive");
        return nullptr;
    }
    CohTable *self = reinterpret_cast<CohTable *>(type->tp_alloc(type, 0));
    if (!self) return nullptr;
    self->mu = new (std::nothrow) std::mutex();
    self->map = new (std::nothrow) std::unordered_map<uint64_t, CohEntry>();
    self->lru = new (std::nothrow) std::list<uint64_t>();
    self->budget = budget;
    self->resident = self->hwm = 0;
    self->evictions = self->pinned_skips = 0;
    self->hits = self->misses = 0;
    self->stage_in_bytes = self->stage_out_bytes = 0;
    if (!self->mu || !self->map || !self->lru) {
        Py_DECREF(self);
        PyErr_NoMemory();
        return nullptr;
    }
    return reinterpret_cast<PyObject *>(self);
}

void coh_dealloc(PyObject *obj) {
    CohTable *self = reinterpret_cast<CohTable *>(obj);
    delete self->mu;
    delete self->map;
    delete self->lru;
    Py_TYPE(obj)->tp_free(obj);
}

// stage_in(key, nbytes, version, write=0, pin=0) -> (need_transfer, victims)
//
// The parsec_device_data_stage_in version check (device_gpu.c:1800) as a
// table decision: need_transfer==0 means a valid copy of exactly this
// version is resident (LRU touched); ==1 means the caller must issue the
// transfer — room was reserved first (the push-phase early reserve), and
// `victims` lists the (key, was_owned) entries the LRU policy evicted to
// make it fit. Python writes OWNED victims back before dropping payloads.
// `pin=1` takes the eviction pin INSIDE the same critical section as the
// reserve — without it, a concurrent stage-in on another thread could
// evict this entry between the reserve and the caller's pin.
PyObject *coh_stage_in(PyObject *obj, PyObject *args) {
    CohTable *self = reinterpret_cast<CohTable *>(obj);
    unsigned long long key;
    long long nbytes;
    unsigned int version;
    int write = 0, pin = 0;
    if (!PyArg_ParseTuple(args, "KLI|ii", &key, &nbytes, &version, &write,
                          &pin))
        return nullptr;
    int need = 0;
    std::vector<std::pair<uint64_t, int>> victims;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        auto it = self->map->find(key);
        if (it != self->map->end() && it->second.state != COH_INVALID &&
            it->second.version == version) {
            self->hits++;
            // MRU touch
            self->lru->erase(it->second.lru_it);
            self->lru->push_back(key);
            it->second.lru_it = std::prev(self->lru->end());
            if (write) it->second.state = COH_OWNED;
        } else {
            need = 1;
            self->misses++;
            self->stage_in_bytes += nbytes;
            int64_t old = it != self->map->end() ? it->second.nbytes : 0;
            coh_make_room_locked(self, nbytes - old, victims,
                                 it != self->map->end(), key);
            it = self->map->find(key);
            if (it != self->map->end()) {
                self->resident += nbytes - it->second.nbytes;
                it->second.nbytes = nbytes;
                self->lru->erase(it->second.lru_it);
            } else {
                CohEntry e;
                e.nbytes = nbytes;
                it = self->map->emplace(key, e).first;
                self->resident += nbytes;
            }
            self->lru->push_back(key);
            it->second.lru_it = std::prev(self->lru->end());
            it->second.version = version;
            it->second.state = write ? COH_OWNED : COH_SHARED;
            if (self->resident > self->hwm) self->hwm = self->resident;
        }
        if (pin) it->second.pins++;
    }
    PyObject *vl = coh_victims_py(victims);
    if (!vl) return nullptr;
    return Py_BuildValue("(iN)", need, vl);
}

// mark_owned(key, version, nbytes) -> victims — a writer completed on the
// device: the copy becomes the OWNER at `version` (the epilog version
// bump, device_gpu.c:3180). The size may change (a body may rebind the
// payload); growth past the budget evicts like stage_in.
PyObject *coh_mark_owned(PyObject *obj, PyObject *args) {
    CohTable *self = reinterpret_cast<CohTable *>(obj);
    unsigned long long key;
    unsigned int version;
    long long nbytes;
    if (!PyArg_ParseTuple(args, "KIL", &key, &version, &nbytes))
        return nullptr;
    std::vector<std::pair<uint64_t, int>> victims;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        auto it = self->map->find(key);
        if (it == self->map->end()) {
            coh_make_room_locked(self, nbytes, victims);
            CohEntry e;
            e.nbytes = nbytes;
            it = self->map->emplace(key, e).first;
            self->resident += nbytes;
            self->lru->push_back(key);
            it->second.lru_it = std::prev(self->lru->end());
        } else {
            int64_t delta = nbytes - it->second.nbytes;
            // exclude the key being marked: evicting it here would hand
            // Python a victim for the copy it is CURRENTLY producing
            // while re-creating the entry resident — table/mirror desync
            if (delta > 0)
                coh_make_room_locked(self, delta, victims, true, key);
            self->resident += nbytes - it->second.nbytes;
            it->second.nbytes = nbytes;
            self->lru->erase(it->second.lru_it);
            self->lru->push_back(key);
            it->second.lru_it = std::prev(self->lru->end());
        }
        it->second.version = version;
        it->second.state = COH_OWNED;
        if (self->resident > self->hwm) self->hwm = self->resident;
    }
    PyObject *vl = coh_victims_py(victims);
    if (!vl) return nullptr;
    return vl;
}

PyObject *coh_pin(PyObject *obj, PyObject *arg) {
    CohTable *self = reinterpret_cast<CohTable *>(obj);
    unsigned long long key = PyLong_AsUnsignedLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    std::lock_guard<std::mutex> lk(*self->mu);
    auto it = self->map->find(key);
    if (it != self->map->end()) it->second.pins++;
    Py_RETURN_NONE;
}

PyObject *coh_unpin(PyObject *obj, PyObject *arg) {
    CohTable *self = reinterpret_cast<CohTable *>(obj);
    unsigned long long key = PyLong_AsUnsignedLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    std::lock_guard<std::mutex> lk(*self->mu);
    auto it = self->map->find(key);
    if (it != self->map->end() && it->second.pins > 0) it->second.pins--;
    Py_RETURN_NONE;
}

// drop(key) -> bool — the payload left the device (Python evicted or the
// data died); the entry leaves residency accounting.
PyObject *coh_drop(PyObject *obj, PyObject *arg) {
    CohTable *self = reinterpret_cast<CohTable *>(obj);
    unsigned long long key = PyLong_AsUnsignedLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    std::lock_guard<std::mutex> lk(*self->mu);
    auto it = self->map->find(key);
    if (it == self->map->end()) Py_RETURN_FALSE;
    self->resident -= it->second.nbytes;
    self->lru->erase(it->second.lru_it);
    self->map->erase(it);
    Py_RETURN_TRUE;
}

// evict(nbytes) -> (victims, pinned_skips) — force ~nbytes of unpinned
// residency out (the explicit half of the OOM retry path, evict_bytes in
// device/tpu.py). The skip count is measured INSIDE the critical section
// so a concurrent stage-in's skips are never attributed to this call.
PyObject *coh_evict(PyObject *obj, PyObject *arg) {
    CohTable *self = reinterpret_cast<CohTable *>(obj);
    long long nbytes = PyLong_AsLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    std::vector<std::pair<uint64_t, int>> victims;
    int64_t skips;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        int64_t target = self->resident - nbytes;
        if (target < 0) target = 0;
        // make_room against a virtual budget of `target`
        int64_t save = self->budget;
        int64_t skips0 = self->pinned_skips;
        self->budget = target;
        coh_make_room_locked(self, 0, victims);
        self->budget = save;
        skips = self->pinned_skips - skips0;
    }
    PyObject *vl = coh_victims_py(victims);
    if (!vl) return nullptr;
    return Py_BuildValue("(NL)", vl, (long long)skips);
}

PyObject *coh_set_budget(PyObject *obj, PyObject *arg) {
    CohTable *self = reinterpret_cast<CohTable *>(obj);
    long long budget = PyLong_AsLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    std::vector<std::pair<uint64_t, int>> victims;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        self->budget = budget;
        coh_make_room_locked(self, 0, victims);
    }
    PyObject *vl = coh_victims_py(victims);
    if (!vl) return nullptr;
    return vl;
}

// state(key) -> (state, version, nbytes, pins) | None
PyObject *coh_state(PyObject *obj, PyObject *arg) {
    CohTable *self = reinterpret_cast<CohTable *>(obj);
    unsigned long long key = PyLong_AsUnsignedLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    std::lock_guard<std::mutex> lk(*self->mu);
    auto it = self->map->find(key);
    if (it == self->map->end()) Py_RETURN_NONE;
    return Py_BuildValue("(iILi)", (int)it->second.state,
                         (unsigned int)it->second.version,
                         (long long)it->second.nbytes,
                         (int)it->second.pins);
}

PyObject *coh_count_writeback(PyObject *obj, PyObject *arg) {
    CohTable *self = reinterpret_cast<CohTable *>(obj);
    long long nbytes = PyLong_AsLongLong(arg);
    if (PyErr_Occurred()) return nullptr;
    std::lock_guard<std::mutex> lk(*self->mu);
    self->stage_out_bytes += nbytes;
    Py_RETURN_NONE;
}

PyObject *coh_stats(PyObject *obj, PyObject *) {
    CohTable *self = reinterpret_cast<CohTable *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    return Py_BuildValue(
        "{s:L,s:L,s:L,s:n,s:L,s:L,s:L,s:L,s:L,s:L}",
        "budget", (long long)self->budget,
        "resident_bytes", (long long)self->resident,
        "hwm_bytes", (long long)self->hwm,
        "entries", (Py_ssize_t)self->map->size(),
        "evictions", (long long)self->evictions,
        "pinned_skips", (long long)self->pinned_skips,
        "coh_hits", (long long)self->hits,
        "coh_misses", (long long)self->misses,
        "stage_in_bytes", (long long)self->stage_in_bytes,
        "stage_out_bytes", (long long)self->stage_out_bytes);
}

PyMethodDef coh_methods[] = {
    {"stage_in", coh_stage_in, METH_VARARGS,
     "stage_in(key, nbytes, version, write=0) -> (need_transfer, "
     "[(victim_key, was_owned)]) — the version-checked residency decision"},
    {"mark_owned", coh_mark_owned, METH_VARARGS,
     "mark_owned(key, version, nbytes) -> victims: writer completed, the "
     "device copy owns `version` now"},
    {"pin", coh_pin, METH_O, "pin(key): protect from eviction walks"},
    {"unpin", coh_unpin, METH_O, "unpin(key)"},
    {"drop", coh_drop, METH_O,
     "drop(key) -> bool: remove from residency accounting"},
    {"evict", coh_evict, METH_O,
     "evict(nbytes) -> (victims, pinned_skips): force ~nbytes of "
     "unpinned residency out"},
    {"set_budget", coh_set_budget, METH_O,
     "set_budget(nbytes) -> victims evicted to fit the new budget"},
    {"state", coh_state, METH_O,
     "state(key) -> (state, version, nbytes, pins) | None"},
    {"count_writeback", coh_count_writeback, METH_O,
     "count_writeback(nbytes): Python performed a D2H write-back"},
    {"stats", coh_stats, METH_NOARGS, "residency/coherency counters"},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject CohTableType = [] {
    PyTypeObject t = {PyVarObject_HEAD_INIT(nullptr, 0)};
    t.tp_name = "parsec_tpu._ptdev.CohTable";
    t.tp_basicsize = sizeof(CohTable);
    t.tp_flags = Py_TPFLAGS_DEFAULT;
    t.tp_doc = "C-side device coherency + LRU residency table (one per "
               "device); Python owns payloads, this owns policy";
    t.tp_new = coh_new;
    t.tp_dealloc = coh_dealloc;
    t.tp_methods = coh_methods;
    return t;
}();

// ---------------------------------------------------------------------------
// Lane — the per-device dispatch/retire plane
// ---------------------------------------------------------------------------

struct PendNode {
    PendNode *next;
    uint32_t pool;
    int32_t tid;
};

constexpr int DEV_MAX_POOLS = 64;

struct PoolEnt {
    bool used = false;
    uint32_t pool_id = 0;
    PtDevRetireVtbl ret{};
    PyObject *engine = nullptr;     // strong ref: pins the retire target
};

struct Lane {
    PyObject_HEAD
    std::atomic<PendNode *> head;   // Treiber MPSC (engines push GIL-free)
    std::mutex *mu;                 // pools + lifecycle + cv
    std::condition_variable *cv;
    std::thread *mgr;
    std::atomic<bool> running;
    PoolEnt *pools;
    PyObject *dispatch_cb;          // dispatch_cb(pool, [tids]) -> issued
    PyObject *poll_cb;              // poll_cb() -> [(pool, tid), ...]
    int poll_us;
    std::atomic<int64_t> inflight;  // dispatched - retired
    // counters
    std::atomic<int64_t> submitted, dispatched, retired, dispatch_batches,
        overlap_hits, late_submits, late_retires, cb_errors;
    bool failed;                    // a callback raised (mu)
    char errmsg[512];               // formatted exception text (mu)
    std::atomic<ptrace_ring::State *> trace;
};

// The GIL-free engine entry (PtDevSubmitVtbl). Wait-free push; the
// condvar notify is lock-free (a sleeping manager's wait_for timeout
// bounds the rare missed-notify window).
void lane_submit_c(void *dev, uint32_t pool, int32_t tid) {
    Lane *self = reinterpret_cast<Lane *>(dev);
    if (!self->running.load(std::memory_order_acquire)) {
        self->late_submits.fetch_add(1, std::memory_order_relaxed);
        return;
    }
    PendNode *n = static_cast<PendNode *>(std::malloc(sizeof(PendNode)));
    if (!n) {       // allocation failure on a GIL-free path: count, drop
        self->late_submits.fetch_add(1, std::memory_order_relaxed);
        return;
    }
    n->pool = pool;
    n->tid = tid;
    n->next = self->head.load(std::memory_order_relaxed);
    while (!self->head.compare_exchange_weak(n->next, n,
                                             std::memory_order_release,
                                             std::memory_order_relaxed)) {
    }
    self->submitted.fetch_add(1, std::memory_order_relaxed);
    self->cv->notify_one();
}

// mu held (or single-threaded init). -1 when not found.
int lane_pool_slot_locked(Lane *self, uint32_t pool) {
    for (int i = 0; i < DEV_MAX_POOLS; i++)
        if (self->pools[i].used && self->pools[i].pool_id == pool) return i;
    return -1;
}

// GIL held. Record a raised Python exception as the lane failure and
// clear it (the manager thread has no caller to propagate to; the
// runtime's drain loops read failed() and surface it).
void lane_record_error(Lane *self) {
    PyObject *type = nullptr, *val = nullptr, *tb = nullptr;
    PyErr_Fetch(&type, &val, &tb);
    PyErr_NormalizeException(&type, &val, &tb);
    char buf[512] = "device lane callback failed";
    if (val) {
        PyObject *s = PyObject_Str(val);
        if (s) {
            const char *c = PyUnicode_AsUTF8(s);
            if (c) std::snprintf(buf, sizeof(buf), "%s", c);
            Py_DECREF(s);
        }
    }
    PyErr_Clear();
    Py_XDECREF(type);
    Py_XDECREF(val);
    Py_XDECREF(tb);
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        self->failed = true;
        std::snprintf(self->errmsg, sizeof(self->errmsg), "%s", buf);
    }
    self->cb_errors.fetch_add(1, std::memory_order_relaxed);
}

// The manager thread (one per lane — the funneled device driver).
void lane_mgr_main(Lane *self) {
    std::vector<std::pair<uint32_t, int32_t>> batch, done;
    std::vector<PtDevRetireVtbl> rets;
    while (self->running.load(std::memory_order_acquire)) {
        // ---- drain the pending MPSC (Treiber pop-all, reverse for FIFO)
        batch.clear();
        PendNode *n = self->head.exchange(nullptr, std::memory_order_acq_rel);
        while (n) {
            batch.emplace_back(n->pool, n->tid);
            PendNode *next = n->next;
            std::free(n);
            n = next;
        }
        std::reverse(batch.begin(), batch.end());
        bool did = false;

        // ---- dispatch phase: GIL taken only to ISSUE the async work
        if (!batch.empty()) {
            ptrace_ring::Writer tw;
            tw.open(self->trace.load(std::memory_order_acquire));
            PyGILState_STATE g = PyGILState_Ensure();
            // every id of this batch ends up either DISPATCHED or counted
            // into late_submits (dropped: stop race, unbound pool, a
            // raising callback) — the fini drain invariant
            // submitted == dispatched + late_submits stays satisfiable
            size_t handled = 0;
            if (self->running.load(std::memory_order_acquire) &&
                self->dispatch_cb) {
                // group contiguous same-pool runs into one callback each
                size_t i = 0;
                while (i < batch.size()) {
                    size_t j = i;
                    uint32_t pool = batch[i].first;
                    while (j < batch.size() && batch[j].first == pool) j++;
                    PyObject *ids = PyList_New((Py_ssize_t)(j - i));
                    if (!ids) { lane_record_error(self); break; }
                    for (size_t k = i; k < j; k++)
                        PyList_SET_ITEM(ids, (Py_ssize_t)(k - i),
                                        PyLong_FromLong(batch[k].second));
                    if (tw.st)
                        tw.rec(EV_DEV_DISPATCH, (int64_t)(j - i),
                               ptrace_ring::FLAG_START);
                    if (self->inflight.load(std::memory_order_relaxed) > 0)
                        self->overlap_hits.fetch_add(
                            1, std::memory_order_relaxed);
                    PyObject *r = PyObject_CallFunction(
                        self->dispatch_cb, "IO", (unsigned int)pool, ids);
                    Py_DECREF(ids);
                    long issued = 0;
                    if (!r) {
                        lane_record_error(self);
                    } else {
                        issued = PyLong_Check(r) ? PyLong_AsLong(r)
                                                 : (long)(j - i);
                        Py_DECREF(r);
                        if (issued < 0) issued = 0;
                        if (issued > (long)(j - i)) issued = (long)(j - i);
                        self->dispatched.fetch_add(
                            issued, std::memory_order_relaxed);
                        self->inflight.fetch_add(issued,
                                                 std::memory_order_relaxed);
                        self->dispatch_batches.fetch_add(
                            1, std::memory_order_relaxed);
                    }
                    if (issued < (long)(j - i))
                        self->late_submits.fetch_add(
                            (long)(j - i) - issued,
                            std::memory_order_relaxed);
                    if (tw.st)
                        tw.rec(EV_DEV_DISPATCH, (int64_t)(j - i),
                               ptrace_ring::FLAG_END);
                    handled = i = j;
                }
            }
            if (handled < batch.size())
                self->late_submits.fetch_add(
                    (int64_t)(batch.size() - handled),
                    std::memory_order_relaxed);
            PyGILState_Release(g);
            did = true;
        }

        // ---- poll phase: ask Python which events completed, then RETIRE
        // them GIL-free into the engines
        if (self->inflight.load(std::memory_order_relaxed) > 0) {
            done.clear();
            rets.clear();
            PyGILState_STATE g = PyGILState_Ensure();
            if (self->running.load(std::memory_order_acquire) &&
                self->poll_cb) {
                PyObject *r = PyObject_CallNoArgs(self->poll_cb);
                if (!r) {
                    lane_record_error(self);
                } else {
                    if (r != Py_None) {
                        PyObject *fast = PySequence_Fast(
                            r, "poll_cb must return a sequence");
                        if (!fast) {
                            lane_record_error(self);
                        } else {
                            Py_ssize_t nd = PySequence_Fast_GET_SIZE(fast);
                            for (Py_ssize_t k = 0; k < nd; k++) {
                                PyObject *it =
                                    PySequence_Fast_GET_ITEM(fast, k);
                                if (!PyTuple_Check(it) ||
                                    PyTuple_GET_SIZE(it) != 2)
                                    continue;
                                long p = PyLong_AsLong(
                                    PyTuple_GET_ITEM(it, 0));
                                long t = PyLong_AsLong(
                                    PyTuple_GET_ITEM(it, 1));
                                if (PyErr_Occurred()) {
                                    PyErr_Clear();
                                    continue;
                                }
                                done.emplace_back((uint32_t)p, (int32_t)t);
                            }
                            Py_DECREF(fast);
                        }
                    }
                    Py_DECREF(r);
                }
            }
            // snapshot the retire vtbls under mu while the GIL pins the
            // pool table against unbinds (bind/unbind hold the GIL)
            {
                std::lock_guard<std::mutex> lk(*self->mu);
                for (auto &pt : done) {
                    int s = lane_pool_slot_locked(self, pt.first);
                    rets.push_back(s >= 0 ? self->pools[s].ret
                                          : PtDevRetireVtbl{0, nullptr,
                                                            nullptr});
                }
            }
            PyGILState_Release(g);
            if (!done.empty()) {
                ptrace_ring::Writer tw;
                tw.open(self->trace.load(std::memory_order_acquire));
                for (size_t k = 0; k < done.size(); k++) {
                    self->inflight.fetch_sub(1, std::memory_order_relaxed);
                    if (!rets[k].retire) {
                        self->late_retires.fetch_add(
                            1, std::memory_order_relaxed);
                        continue;
                    }
                    // the GIL-free landing into the engine's release walk
                    rets[k].retire(rets[k].obj, done[k].second);
                    self->retired.fetch_add(1, std::memory_order_relaxed);
                    if (tw.st)
                        tw.rec(EV_DEV_RETIRE, done[k].second,
                               ptrace_ring::FLAG_POINT);
                }
                did = true;
            }
        }

        if (!did) {
            std::unique_lock<std::mutex> lk(*self->mu);
            if (self->head.load(std::memory_order_acquire) == nullptr &&
                self->running.load(std::memory_order_acquire)) {
                // in-flight work: short event-poll cadence; idle: park
                // (a submit's lock-free notify — or the timeout — wakes us)
                auto dt = self->inflight.load(std::memory_order_relaxed) > 0
                              ? std::chrono::microseconds(self->poll_us)
                              : std::chrono::milliseconds(2);
                self->cv->wait_for(lk, dt);
            }
        }
    }
}

PyObject *lane_new(PyTypeObject *type, PyObject *, PyObject *) {
    Lane *self = reinterpret_cast<Lane *>(type->tp_alloc(type, 0));
    if (!self) return nullptr;
    new (&self->head) std::atomic<PendNode *>(nullptr);
    self->mu = new (std::nothrow) std::mutex();
    self->cv = new (std::nothrow) std::condition_variable();
    self->mgr = nullptr;
    new (&self->running) std::atomic<bool>(false);
    self->pools = new (std::nothrow) PoolEnt[DEV_MAX_POOLS];
    self->dispatch_cb = self->poll_cb = nullptr;
    self->poll_us = 100;
    new (&self->inflight) std::atomic<int64_t>(0);
    new (&self->submitted) std::atomic<int64_t>(0);
    new (&self->dispatched) std::atomic<int64_t>(0);
    new (&self->retired) std::atomic<int64_t>(0);
    new (&self->dispatch_batches) std::atomic<int64_t>(0);
    new (&self->overlap_hits) std::atomic<int64_t>(0);
    new (&self->late_submits) std::atomic<int64_t>(0);
    new (&self->late_retires) std::atomic<int64_t>(0);
    new (&self->cb_errors) std::atomic<int64_t>(0);
    self->failed = false;
    self->errmsg[0] = '\0';
    new (&self->trace) std::atomic<ptrace_ring::State *>(nullptr);
    if (!self->mu || !self->cv || !self->pools) {
        Py_DECREF(self);
        PyErr_NoMemory();
        return nullptr;
    }
    return reinterpret_cast<PyObject *>(self);
}

void lane_stop_impl(Lane *self) {
    if (!self->running.exchange(false, std::memory_order_acq_rel)) return;
    self->cv->notify_all();
    if (self->mgr) {
        // the manager may be blocked in PyGILState_Ensure: release the
        // GIL around the join so it can finish its iteration and exit
        Py_BEGIN_ALLOW_THREADS;
        self->mgr->join();
        Py_END_ALLOW_THREADS;
        delete self->mgr;
        self->mgr = nullptr;
    }
    // drop callbacks (GIL held) and the stranded pending queue
    Py_CLEAR(self->dispatch_cb);
    Py_CLEAR(self->poll_cb);
    PendNode *n = self->head.exchange(nullptr, std::memory_order_acq_rel);
    while (n) {
        PendNode *next = n->next;
        std::free(n);
        self->late_submits.fetch_add(1, std::memory_order_relaxed);
        n = next;
    }
}

void lane_dealloc(PyObject *obj) {
    Lane *self = reinterpret_cast<Lane *>(obj);
    lane_stop_impl(self);
    if (self->pools)
        for (int i = 0; i < DEV_MAX_POOLS; i++)
            Py_CLEAR(self->pools[i].engine);
    delete[] self->pools;
    delete self->mu;
    delete self->cv;
    delete self->trace.load(std::memory_order_acquire);
    Py_TYPE(obj)->tp_free(obj);
}

// start(dispatch_cb, poll_cb, poll_us=100) — spawn the manager thread.
PyObject *lane_start(PyObject *obj, PyObject *args) {
    Lane *self = reinterpret_cast<Lane *>(obj);
    PyObject *dcb, *pcb;
    int poll_us = 100;
    if (!PyArg_ParseTuple(args, "OO|i", &dcb, &pcb, &poll_us))
        return nullptr;
    if (!PyCallable_Check(dcb) || !PyCallable_Check(pcb)) {
        PyErr_SetString(PyExc_TypeError, "callbacks must be callable");
        return nullptr;
    }
    if (self->running.load(std::memory_order_acquire)) {
        PyErr_SetString(PyExc_RuntimeError, "lane already started");
        return nullptr;
    }
    Py_INCREF(dcb);
    Py_INCREF(pcb);
    self->dispatch_cb = dcb;
    self->poll_cb = pcb;
    self->poll_us = poll_us > 0 ? poll_us : 100;
    self->running.store(true, std::memory_order_release);
    self->mgr = new (std::nothrow) std::thread(lane_mgr_main, self);
    if (!self->mgr) {
        self->running.store(false, std::memory_order_release);
        Py_CLEAR(self->dispatch_cb);
        Py_CLEAR(self->poll_cb);
        return PyErr_NoMemory();
    }
    Py_RETURN_NONE;
}

PyObject *lane_stop(PyObject *obj, PyObject *) {
    lane_stop_impl(reinterpret_cast<Lane *>(obj));
    Py_RETURN_NONE;
}

// bind_pool(pool_id, retire_capsule, engine) — route pool's completions
// into `engine` through its retire vtable; the engine object is pinned
// for the bind window.
PyObject *lane_bind_pool(PyObject *obj, PyObject *args) {
    Lane *self = reinterpret_cast<Lane *>(obj);
    unsigned int pool;
    PyObject *cap, *engine;
    if (!PyArg_ParseTuple(args, "IOO", &pool, &cap, &engine))
        return nullptr;
    PtDevRetireVtbl *rv = static_cast<PtDevRetireVtbl *>(
        PyCapsule_GetPointer(cap, PTDEV_RETIRE_CAPSULE));
    if (!rv) return nullptr;
    if (rv->abi != PTDEV_ABI) {
        PyErr_SetString(PyExc_RuntimeError, "ptdev ABI mismatch");
        return nullptr;
    }
    std::lock_guard<std::mutex> lk(*self->mu);
    if (lane_pool_slot_locked(self, pool) >= 0) {
        PyErr_SetString(PyExc_ValueError, "pool id already bound");
        return nullptr;
    }
    for (int i = 0; i < DEV_MAX_POOLS; i++) {
        if (!self->pools[i].used) {
            self->pools[i].used = true;
            self->pools[i].pool_id = pool;
            self->pools[i].ret = *rv;
            Py_INCREF(engine);
            self->pools[i].engine = engine;
            Py_RETURN_NONE;
        }
    }
    PyErr_SetString(PyExc_RuntimeError, "device lane pool table full");
    return nullptr;
}

PyObject *lane_unbind_pool(PyObject *obj, PyObject *arg) {
    Lane *self = reinterpret_cast<Lane *>(obj);
    unsigned long pool = PyLong_AsUnsignedLong(arg);
    if (PyErr_Occurred()) return nullptr;
    PyObject *drop = nullptr;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        int s = lane_pool_slot_locked(self, (uint32_t)pool);
        if (s < 0) Py_RETURN_FALSE;
        self->pools[s].used = false;
        self->pools[s].ret = PtDevRetireVtbl{0, nullptr, nullptr};
        drop = self->pools[s].engine;
        self->pools[s].engine = nullptr;
    }
    Py_XDECREF(drop);     // outside mu: __del__ may re-enter the lane
    Py_RETURN_TRUE;
}

void submit_capsule_free(PyObject *cap) {
    std::free(PyCapsule_GetPointer(cap, PTDEV_SUBMIT_CAPSULE));
}

// submit_capsule() -> PyCapsule(PtDevSubmitVtbl) for Graph.dev_bind /
// Engine.dev_bind. Borrows `self`: the Python device lane keeps the Lane
// alive for every bound graph's lifetime (ptdev_iface.h lifetime rules).
PyObject *lane_submit_capsule(PyObject *obj, PyObject *) {
    PtDevSubmitVtbl *v =
        static_cast<PtDevSubmitVtbl *>(std::malloc(sizeof(PtDevSubmitVtbl)));
    if (!v) return PyErr_NoMemory();
    v->abi = PTDEV_ABI;
    v->dev = obj;
    v->submit = lane_submit_c;
    PyObject *cap = PyCapsule_New(v, PTDEV_SUBMIT_CAPSULE,
                                  submit_capsule_free);
    if (!cap) std::free(v);
    return cap;
}

// submit(pool, tid) — Python mirror of the C entry (tests, seeding)
PyObject *lane_submit(PyObject *obj, PyObject *args) {
    unsigned int pool;
    int tid;
    if (!PyArg_ParseTuple(args, "Ii", &pool, &tid)) return nullptr;
    lane_submit_c(obj, pool, (int32_t)tid);
    Py_RETURN_NONE;
}

PyObject *lane_notify(PyObject *obj, PyObject *) {
    reinterpret_cast<Lane *>(obj)->cv->notify_one();
    Py_RETURN_NONE;
}

PyObject *lane_failed(PyObject *obj, PyObject *) {
    Lane *self = reinterpret_cast<Lane *>(obj);
    std::lock_guard<std::mutex> lk(*self->mu);
    if (!self->failed) Py_RETURN_NONE;
    return PyUnicode_FromString(self->errmsg);
}

PyObject *lane_stats(PyObject *obj, PyObject *) {
    Lane *self = reinterpret_cast<Lane *>(obj);
    int npools = 0;
    {
        std::lock_guard<std::mutex> lk(*self->mu);
        for (int i = 0; i < DEV_MAX_POOLS; i++)
            if (self->pools[i].used) npools++;
    }
    return Py_BuildValue(
        "{s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:i}",
        "submitted", (long long)self->submitted.load(),
        "dispatched", (long long)self->dispatched.load(),
        "retired", (long long)self->retired.load(),
        "dispatch_batches", (long long)self->dispatch_batches.load(),
        "overlap_hits", (long long)self->overlap_hits.load(),
        "late_submits", (long long)self->late_submits.load(),
        "late_retires", (long long)self->late_retires.load(),
        "cb_errors", (long long)self->cb_errors.load(),
        "inflight", (long long)self->inflight.load(),
        "pools", npools);
}

// ------------------------------------------------------- in-lane tracing
PyObject *lane_trace_enable(PyObject *obj, PyObject *args) {
    return ptrace_ring::py_trace_enable(
        reinterpret_cast<Lane *>(obj)->trace, args);
}

PyObject *lane_trace_disable(PyObject *obj, PyObject *) {
    return ptrace_ring::py_trace_disable(
        reinterpret_cast<Lane *>(obj)->trace.load(std::memory_order_acquire));
}

PyObject *lane_trace_drain(PyObject *obj, PyObject *) {
    return ptrace_ring::py_trace_drain(
        reinterpret_cast<Lane *>(obj)->trace.load(std::memory_order_acquire));
}

PyObject *lane_trace_dropped(PyObject *obj, PyObject *) {
    return ptrace_ring::py_trace_dropped(
        reinterpret_cast<Lane *>(obj)->trace.load(std::memory_order_acquire));
}

PyObject *lane_monotonic_ns(PyObject *, PyObject *) {
    return PyLong_FromLongLong(ptrace_ring::now_ns());
}

PyMethodDef lane_methods[] = {
    {"start", lane_start, METH_VARARGS,
     "start(dispatch_cb, poll_cb, poll_us=100): spawn the manager thread"},
    {"stop", lane_stop, METH_NOARGS,
     "stop the manager thread (idempotent; joins with the GIL released)"},
    {"bind_pool", lane_bind_pool, METH_VARARGS,
     "bind_pool(pool_id, retire_capsule, engine): route completions into "
     "the engine's GIL-free retire entry"},
    {"unbind_pool", lane_unbind_pool, METH_O,
     "unbind_pool(pool_id) -> bool: stop routing (straggler retires are "
     "counted late_retires, never trusted)"},
    {"submit_capsule", lane_submit_capsule, METH_NOARGS,
     "PyCapsule(PtDevSubmitVtbl) for the engines' dev_bind"},
    {"submit", lane_submit, METH_VARARGS,
     "submit(pool, tid): Python mirror of the GIL-free submit entry"},
    {"notify", lane_notify, METH_NOARGS, "wake a parked manager thread"},
    {"failed", lane_failed, METH_NOARGS,
     "None, or the message of the callback exception that poisoned the "
     "lane"},
    {"stats", lane_stats, METH_NOARGS, "lane counters"},
    {"trace_enable", lane_trace_enable, METH_VARARGS,
     "arm the in-lane event rings (EV_DEV_DISPATCH/EV_DEV_RETIRE)"},
    {"trace_disable", lane_trace_disable, METH_NOARGS, "stop recording"},
    {"trace_drain", lane_trace_drain, METH_NOARGS,
     "trace_drain() -> [(ring_id, packed_events_bytes)]"},
    {"trace_dropped", lane_trace_dropped, METH_NOARGS,
     "cumulative events lost to ring overflow"},
    {"monotonic_ns", lane_monotonic_ns, METH_NOARGS,
     "the trace clock (steady_clock ns)"},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject LaneType = [] {
    PyTypeObject t = {PyVarObject_HEAD_INIT(nullptr, 0)};
    t.tp_name = "parsec_tpu._ptdev.Lane";
    t.tp_basicsize = sizeof(Lane);
    t.tp_flags = Py_TPFLAGS_DEFAULT;
    t.tp_doc = "per-device async dispatch/retire plane (manager thread + "
               "MPSC pending queue + GIL-free retirement)";
    t.tp_new = lane_new;
    t.tp_dealloc = lane_dealloc;
    t.tp_methods = lane_methods;
    return t;
}();

PyModuleDef ptdev_module = {
    PyModuleDef_HEAD_INIT, "_ptdev",
    "native device lane (see native/src/ptdev.cpp)", -1,
    nullptr, nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__ptdev(void) {
    if (PyType_Ready(&LaneType) < 0 || PyType_Ready(&CohTableType) < 0)
        return nullptr;
    PyObject *m = PyModule_Create(&ptdev_module);
    if (!m) return nullptr;
    Py_INCREF(&LaneType);
    if (PyModule_AddObject(m, "Lane",
                           reinterpret_cast<PyObject *>(&LaneType)) < 0) {
        Py_DECREF(&LaneType);
        Py_DECREF(m);
        return nullptr;
    }
    Py_INCREF(&CohTableType);
    if (PyModule_AddObject(m, "CohTable",
                           reinterpret_cast<PyObject *>(&CohTableType)) < 0) {
        Py_DECREF(&CohTableType);
        Py_DECREF(m);
        return nullptr;
    }
    if (PyModule_AddIntConstant(m, "EV_DEV_DISPATCH", EV_DEV_DISPATCH) < 0 ||
        PyModule_AddIntConstant(m, "EV_DEV_RETIRE", EV_DEV_RETIRE) < 0 ||
        PyModule_AddIntConstant(m, "COH_INVALID", COH_INVALID) < 0 ||
        PyModule_AddIntConstant(m, "COH_OWNED", COH_OWNED) < 0 ||
        PyModule_AddIntConstant(m, "COH_SHARED", COH_SHARED) < 0 ||
        PyModule_AddIntConstant(m, "MAX_POOLS", DEV_MAX_POOLS) < 0) {
        Py_DECREF(m);
        return nullptr;
    }
    return m;
}
