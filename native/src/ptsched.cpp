// parsec_tpu._ptsched — the multi-pool scheduler plane as a CPython
// extension (see native/src/ptsched.h for the machinery; this file is
// only the Python surface + the capsule that hands the live plane to the
// execution engines).
//
// One Plane per Context (core/sched_plane.py owns the lifecycle): pools
// register with a QoS weight and an admission window, the engines bind
// through plane_capsule(), and every counter the plane keeps (steals,
// spills, per-pool served/deficit, admission stalls) is readable here for
// the unified registry (`sched.*`). The `queue_ns` histogram (push ->
// pop wait, sampled 1-in-8 by task id) snapshots through the same
// pthist.h surface as the lanes' histograms.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <new>
#include <vector>

#include "pthist.h"
#include "ptsched.h"

namespace {

using ptsched::Item;
using ptsched::Plane;

const char *const HIST_NAMES[1] = {"queue_ns"};

struct PyPlane {
    PyObject_HEAD
    Plane *plane;
};

PyObject *plane_new(PyTypeObject *type, PyObject *args, PyObject *kw) {
    static const char *kws[] = {"nworkers", "policy", "quantum", nullptr};
    int nworkers = 1, policy = ptsched::POLICY_WDRR;
    long long quantum = 256;
    if (!PyArg_ParseTupleAndKeywords(args, kw, "|iiL",
                                     const_cast<char **>(kws), &nworkers,
                                     &policy, &quantum))
        return nullptr;
    if (policy < ptsched::POLICY_FIFO || policy > ptsched::POLICY_RNDSTEAL) {
        PyErr_SetString(PyExc_ValueError, "unknown policy");
        return nullptr;
    }
    PyPlane *self = reinterpret_cast<PyPlane *>(type->tp_alloc(type, 0));
    if (!self) return nullptr;
    self->plane = new (std::nothrow) Plane(nworkers, policy, quantum);
    if (!self->plane) {
        Py_DECREF(self);
        PyErr_NoMemory();
        return nullptr;
    }
    return reinterpret_cast<PyObject *>(self);
}

void plane_dealloc(PyObject *obj) {
    delete reinterpret_cast<PyPlane *>(obj)->plane;
    Py_TYPE(obj)->tp_free(obj);
}

inline Plane *P(PyObject *obj) {
    return reinterpret_cast<PyPlane *>(obj)->plane;
}

bool check_handle(Plane *pl, long h) {
    (void)pl;
    if (h < 0 || h >= ptsched::MAX_POOLS) {
        PyErr_SetString(PyExc_IndexError, "bad pool handle");
        return false;
    }
    return true;
}

// register_pool(ext_id, kind, weight=1, window=0) -> handle
PyObject *plane_register_pool(PyObject *obj, PyObject *args, PyObject *kw) {
    static const char *kws[] = {"ext_id", "kind", "weight", "window",
                                nullptr};
    unsigned int ext_id = 0;
    int kind = ptsched::KIND_EXT, weight = 1;
    long long window = 0;
    if (!PyArg_ParseTupleAndKeywords(args, kw, "|IiiL",
                                     const_cast<char **>(kws), &ext_id,
                                     &kind, &weight, &window))
        return nullptr;
    int h = P(obj)->pool_register(ext_id, kind, weight, window);
    if (h < 0) {
        PyErr_SetString(PyExc_RuntimeError, "scheduler pool table full");
        return nullptr;
    }
    return PyLong_FromLong(h);
}

PyObject *plane_unregister_pool(PyObject *obj, PyObject *arg) {
    long h = PyLong_AsLong(arg);
    if (h == -1 && PyErr_Occurred()) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    P(obj)->pool_unregister((int)h);
    Py_RETURN_NONE;
}

// push(h, tids, prios=None, worker=-1) -> bool (over admission window)
PyObject *plane_push(PyObject *obj, PyObject *args, PyObject *kw) {
    static const char *kws[] = {"h", "tids", "prios", "worker", nullptr};
    long h;
    PyObject *tids_o, *prios_o = Py_None;
    int worker = -1;
    if (!PyArg_ParseTupleAndKeywords(args, kw, "lO|Oi",
                                     const_cast<char **>(kws), &h, &tids_o,
                                     &prios_o, &worker))
        return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    std::vector<int32_t> tids, prios;
    PyObject *fast = PySequence_Fast(tids_o, "tids: sequence of ints");
    if (!fast) return nullptr;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    tids.reserve((size_t)n);
    for (Py_ssize_t i = 0; i < n; i++) {
        long v = PyLong_AsLong(PySequence_Fast_GET_ITEM(fast, i));
        if (v == -1 && PyErr_Occurred()) { Py_DECREF(fast); return nullptr; }
        tids.push_back((int32_t)v);
    }
    Py_DECREF(fast);
    if (prios_o != Py_None) {
        fast = PySequence_Fast(prios_o, "prios: sequence of ints");
        if (!fast) return nullptr;
        if (PySequence_Fast_GET_SIZE(fast) != n) {
            Py_DECREF(fast);
            PyErr_SetString(PyExc_ValueError, "tids/prios length mismatch");
            return nullptr;
        }
        prios.reserve((size_t)n);
        for (Py_ssize_t i = 0; i < n; i++) {
            long v = PyLong_AsLong(PySequence_Fast_GET_ITEM(fast, i));
            if (v == -1 && PyErr_Occurred()) {
                Py_DECREF(fast);
                return nullptr;
            }
            prios.push_back((int32_t)v);
        }
        Py_DECREF(fast);
    }
    bool over = P(obj)->push((int)h, worker, tids.data(),
                             prios.empty() ? nullptr : prios.data(),
                             (int)n);
    return PyBool_FromLong(over ? 1 : 0);
}

// pop(worker=0, kind=-1, pool=-1, cap=256) -> [(pool, tid), ...]
PyObject *plane_pop(PyObject *obj, PyObject *args, PyObject *kw) {
    static const char *kws[] = {"worker", "kind", "pool", "cap", nullptr};
    int worker = 0, kind = ptsched::KIND_ANY, pool = -1, cap = 256;
    if (!PyArg_ParseTupleAndKeywords(args, kw, "|iiii",
                                     const_cast<char **>(kws), &worker,
                                     &kind, &pool, &cap))
        return nullptr;
    if (cap <= 0) cap = 256;
    std::vector<Item> out((size_t)cap);
    int n;
    Py_BEGIN_ALLOW_THREADS
    n = P(obj)->pop(worker, kind, pool, out.data(), cap);
    Py_END_ALLOW_THREADS
    PyObject *lst = PyList_New((Py_ssize_t)n);
    if (!lst) return nullptr;
    for (int i = 0; i < n; i++) {
        PyObject *t = Py_BuildValue("(ii)", (int)out[(size_t)i].pool,
                                    (int)out[(size_t)i].tid);
        if (!t) { Py_DECREF(lst); return nullptr; }
        PyList_SET_ITEM(lst, (Py_ssize_t)i, t);
    }
    return lst;
}

PyObject *plane_admit(PyObject *obj, PyObject *args) {
    long h;
    long long n = 1;
    if (!PyArg_ParseTuple(args, "l|L", &h, &n)) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    P(obj)->admit((int)h, n);
    Py_RETURN_NONE;
}

PyObject *plane_retired(PyObject *obj, PyObject *args) {
    long h;
    long long n = 1;
    if (!PyArg_ParseTuple(args, "l|L", &h, &n)) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    P(obj)->retired((int)h, n);
    Py_RETURN_NONE;
}

PyObject *plane_inflight(PyObject *obj, PyObject *arg) {
    long h = PyLong_AsLong(arg);
    if (h == -1 && PyErr_Occurred()) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    return PyLong_FromLongLong(P(obj)->inflight_of((int)h));
}

PyObject *plane_over_window(PyObject *obj, PyObject *arg) {
    long h = PyLong_AsLong(arg);
    if (h == -1 && PyErr_Occurred()) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    return PyBool_FromLong(P(obj)->over_window((int)h) ? 1 : 0);
}

PyObject *plane_remote_grant(PyObject *obj, PyObject *args) {
    long h;
    long long n = 1;
    if (!PyArg_ParseTuple(args, "l|L", &h, &n)) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    P(obj)->remote_grant((int)h, n);
    Py_RETURN_NONE;
}

PyObject *plane_remote_release(PyObject *obj, PyObject *args) {
    long h;
    long long n = 1;
    if (!PyArg_ParseTuple(args, "l|L", &h, &n)) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    P(obj)->remote_release((int)h, n);
    Py_RETURN_NONE;
}

PyObject *plane_remote_granted(PyObject *obj, PyObject *arg) {
    long h = PyLong_AsLong(arg);
    if (h == -1 && PyErr_Occurred()) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    return PyLong_FromLongLong(P(obj)->remote_granted_of((int)h));
}

PyObject *plane_headroom(PyObject *obj, PyObject *arg) {
    long h = PyLong_AsLong(arg);
    if (h == -1 && PyErr_Occurred()) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    return PyLong_FromLongLong(P(obj)->headroom_of((int)h));
}

PyObject *plane_set_weight(PyObject *obj, PyObject *args) {
    long h;
    int w;
    if (!PyArg_ParseTuple(args, "li", &h, &w)) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    P(obj)->set_weight((int)h, (int32_t)w);
    Py_RETURN_NONE;
}

PyObject *plane_stall(PyObject *obj, PyObject *arg) {
    long h = PyLong_AsLong(arg);
    if (h == -1 && PyErr_Occurred()) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    Plane *pl = P(obj);
    pl->pools[h].stalls.fetch_add(1, std::memory_order_relaxed);
    pl->admission_stalls.fetch_add(1, std::memory_order_relaxed);
    Py_RETURN_NONE;
}

PyObject *plane_queued(PyObject *obj, PyObject *arg) {
    long h = PyLong_AsLong(arg);
    if (h == -1 && PyErr_Occurred()) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    return PyLong_FromLongLong(P(obj)->queued_of((int)h));
}

PyObject *plane_queued_kind(PyObject *obj, PyObject *args) {
    int kind = ptsched::KIND_ANY;
    if (!PyArg_ParseTuple(args, "|i", &kind)) return nullptr;
    return PyLong_FromLongLong(P(obj)->queued_kind(kind));
}

// next_pool(kind=-1) -> (handle, quantum) or None
PyObject *plane_next_pool(PyObject *obj, PyObject *args) {
    int kind = ptsched::KIND_ANY;
    if (!PyArg_ParseTuple(args, "|i", &kind)) return nullptr;
    int64_t q = 0;
    int h = P(obj)->next_pool(kind, &q);
    if (h < 0) Py_RETURN_NONE;
    return Py_BuildValue("(iL)", h, (long long)q);
}

PyObject *plane_charge(PyObject *obj, PyObject *args) {
    long h;
    long long n;
    if (!PyArg_ParseTuple(args, "lL", &h, &n)) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    P(obj)->charge((int)h, n);
    Py_RETURN_NONE;
}

PyObject *plane_deficit(PyObject *obj, PyObject *arg) {
    long h = PyLong_AsLong(arg);
    if (h == -1 && PyErr_Occurred()) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    return PyLong_FromLongLong(P(obj)->deficit_of((int)h));
}

PyObject *plane_stats(PyObject *obj, PyObject *) {
    Plane *pl = P(obj);
    int64_t steals = 0;
    for (int w = 0; w < pl->nworkers; w++)
        steals += pl->steals[w].load(std::memory_order_relaxed);
    int64_t queued = 0;
    for (int i = 0; i < ptsched::MAX_POOLS; i++) {
        ptsched::Pool &p = pl->pools[i];
        if (p.live) queued += p.queued.load(std::memory_order_relaxed);
    }
    // served/spills/stalls come from the plane-LIFETIME accumulators:
    // per-pool counters reset when a freed slot is re-registered, so
    // summing them would make these metrics go BACKWARDS (found by the
    // verify drive: a second wave of pools wiped the first wave's served)
    return Py_BuildValue(
        "{s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:i,s:i}",
        "steals", (long long)steals,
        "steal_visits",
        (long long)pl->steal_visits.load(std::memory_order_relaxed),
        "spills",
        (long long)pl->spills_total.load(std::memory_order_relaxed),
        "served",
        (long long)pl->served_total.load(std::memory_order_relaxed),
        "admission_stalls",
        (long long)pl->admission_stalls.load(std::memory_order_relaxed),
        "weight_adjusts",
        (long long)pl->weight_adjusts.load(std::memory_order_relaxed),
        "queued", (long long)queued,
        "pools_registered",
        (long long)pl->pools_registered.load(std::memory_order_relaxed),
        "pools_live",
        (long long)pl->pools_live.load(std::memory_order_relaxed),
        "policy", pl->policy, "nworkers", pl->nworkers);
}

PyObject *plane_worker_steals(PyObject *obj, PyObject *arg) {
    long w = PyLong_AsLong(arg);
    if (w == -1 && PyErr_Occurred()) return nullptr;
    Plane *pl = P(obj);
    if (w < 0 || w >= pl->nworkers) {
        PyErr_SetString(PyExc_IndexError, "bad worker id");
        return nullptr;
    }
    return PyLong_FromLongLong(
        pl->steals[w].load(std::memory_order_relaxed));
}

PyObject *plane_pool_stats(PyObject *obj, PyObject *arg) {
    long h = PyLong_AsLong(arg);
    if (h == -1 && PyErr_Occurred()) return nullptr;
    if (!check_handle(P(obj), h)) return nullptr;
    ptsched::Pool &p = P(obj)->pools[h];
    return Py_BuildValue(
        "{s:O,s:i,s:i,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:I}",
        "live", p.live ? Py_True : Py_False,
        "kind", p.kind,
        "weight", (int)p.weight.load(std::memory_order_relaxed),
        "window", (long long)p.window,
        "queued", (long long)p.queued.load(std::memory_order_relaxed),
        "inflight", (long long)p.inflight.load(std::memory_order_relaxed),
        "remote_granted",
        (long long)p.remote_granted.load(std::memory_order_relaxed),
        "served", (long long)p.served.load(std::memory_order_relaxed),
        "spills", (long long)p.spills.load(std::memory_order_relaxed),
        "stalls", (long long)p.stalls.load(std::memory_order_relaxed),
        "ext_id", (unsigned int)p.ext_id);
}

// ------------------------------------------------------------- the capsule
// plane_capsule() -> PyCapsule(Plane*). The capsule owns one strong
// reference to this Plane OBJECT (its context pointer): an engine that
// stores the capsule keeps the plane alive for the binding window, the
// ptcomm_iface.h lifetime discipline without a second Python object.
void plane_capsule_free(PyObject *cap) {
    PyObject *owner =
        static_cast<PyObject *>(PyCapsule_GetContext(cap));
    Py_XDECREF(owner);
}

PyObject *plane_capsule(PyObject *obj, PyObject *) {
    PyObject *cap = PyCapsule_New(P(obj), PTSCHED_PLANE_CAPSULE,
                                  plane_capsule_free);
    if (!cap) return nullptr;
    Py_INCREF(obj);
    if (PyCapsule_SetContext(cap, obj) < 0) {
        Py_DECREF(obj);
        Py_DECREF(cap);
        return nullptr;
    }
    return cap;
}

// --------------------------------------------------- latency histograms
PyObject *plane_hist_enable(PyObject *obj, PyObject *) {
    return pthist::py_hist_enable<1>(P(obj)->hist);
}

PyObject *plane_hist_disable(PyObject *obj, PyObject *) {
    return pthist::py_hist_disable<1>(
        P(obj)->hist.load(std::memory_order_acquire));
}

PyObject *plane_hist_snapshot(PyObject *obj, PyObject *) {
    return pthist::py_hist_snapshot<1>(
        P(obj)->hist.load(std::memory_order_acquire), HIST_NAMES);
}

PyMethodDef plane_methods[] = {
    {"register_pool", reinterpret_cast<PyCFunction>(plane_register_pool),
     METH_VARARGS | METH_KEYWORDS,
     "register_pool(ext_id=0, kind=KIND_EXT, weight=1, window=0) -> "
     "handle: admit a pool to the plane (weight = DRR share, window = "
     "admission soft limit, 0 = unlimited)"},
    {"unregister_pool", plane_unregister_pool, METH_O,
     "drop a pool: sweep its items out of every queue, free the slot"},
    {"push", reinterpret_cast<PyCFunction>(plane_push),
     METH_VARARGS | METH_KEYWORDS,
     "push(h, tids, prios=None, worker=-1) -> over_window: enqueue ready "
     "items (worker >= 0 routes via that worker's hot queue)"},
    {"pop", reinterpret_cast<PyCFunction>(plane_pop),
     METH_VARARGS | METH_KEYWORDS,
     "pop(worker=0, kind=-1, pool=-1, cap=256) -> [(pool, tid)]: hot "
     "queue, then DRR overflow refill, then steal-half"},
    {"admit", plane_admit, METH_VARARGS,
     "admit(h, n=1): n tasks entered the pool (admission accounting)"},
    {"retired", plane_retired, METH_VARARGS,
     "retired(h, n=1): n tasks completed (admission accounting)"},
    {"inflight", plane_inflight, METH_O,
     "admitted-minus-retired tasks of pool h"},
    {"over_window", plane_over_window, METH_O,
     "True when pool h is past its admission window (local inflight + "
     "remote grants share the budget)"},
    {"remote_grant", plane_remote_grant, METH_VARARGS,
     "remote_grant(h, n=1): reserve window room for credits granted to "
     "remote inserters (ptfab)"},
    {"remote_release", plane_remote_release, METH_VARARGS,
     "remote_release(h, n=1): release reserved remote window room "
     "(arrival/return/reclaim; floors at 0)"},
    {"remote_granted", plane_remote_granted, METH_O,
     "window room currently reserved for remote inserters of pool h"},
    {"headroom", plane_headroom, METH_O,
     "grantable window room of pool h (window - inflight - "
     "remote_granted), -1 = unlimited"},
    {"set_weight", plane_set_weight, METH_VARARGS,
     "set_weight(h, w): mid-run QoS weight nudge (the ptfab "
     "reconciliation entry; binds at the next DRR round top-up)"},
    {"stall", plane_stall, METH_O,
     "count one admission stall against pool h"},
    {"queued", plane_queued, METH_O,
     "ready items of pool h currently in the plane"},
    {"queued_kind", plane_queued_kind, METH_VARARGS,
     "queued_kind(kind=-1) -> total ready items across live pools"},
    {"next_pool", plane_next_pool, METH_VARARGS,
     "next_pool(kind=-1) -> (handle, quantum) | None: DRR pick among "
     "pools with queued work"},
    {"charge", plane_charge, METH_VARARGS,
     "charge(h, n): spend n DRR credits of pool h"},
    {"deficit", plane_deficit, METH_O,
     "current DRR deficit (unspent credits) of pool h"},
    {"stats", plane_stats, METH_NOARGS,
     "{steals, steal_visits, spills, served, admission_stalls, queued, "
     "pools_registered, pools_live, policy, nworkers}"},
    {"worker_steals", plane_worker_steals, METH_O,
     "items stolen BY worker w"},
    {"pool_stats", plane_pool_stats, METH_O,
     "per-pool counters {live, kind, weight, window, queued, inflight, "
     "served, spills, stalls, ext_id}"},
    {"plane_capsule", plane_capsule, METH_NOARGS,
     "PyCapsule(Plane*) for Graph.sched_bind / Engine.sched_bind; the "
     "capsule keeps this plane alive"},
    {"hist_enable", plane_hist_enable, METH_NOARGS,
     "arm the sched.queue_ns histogram (push->pop wait, sampled 1-in-8)"},
    {"hist_disable", plane_hist_disable, METH_NOARGS,
     "stop recording (buckets are kept)"},
    {"hist_snapshot", plane_hist_snapshot, METH_NOARGS,
     "{name: (count, sum_ns, buckets_bytes)} — buckets pack '<496Q'"},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject PlaneType = [] {
    PyTypeObject t = {PyVarObject_HEAD_INIT(nullptr, 0)};
    t.tp_name = "parsec_tpu._ptsched.Plane";
    t.tp_basicsize = sizeof(PyPlane);
    t.tp_flags = Py_TPFLAGS_DEFAULT;
    t.tp_doc = "native multi-pool scheduler plane (see native/src/ptsched.h)";
    t.tp_new = plane_new;
    t.tp_dealloc = plane_dealloc;
    t.tp_methods = plane_methods;
    return t;
}();

PyModuleDef ptsched_module = {
    PyModuleDef_HEAD_INIT, "_ptsched",
    "native multi-pool scheduler plane (see native/src/ptsched.h)", -1,
    nullptr, nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__ptsched(void) {
    if (PyType_Ready(&PlaneType) < 0) return nullptr;
    PyObject *m = PyModule_Create(&ptsched_module);
    if (!m) return nullptr;
    Py_INCREF(&PlaneType);
    if (PyModule_AddObject(m, "Plane",
                           reinterpret_cast<PyObject *>(&PlaneType)) < 0) {
        Py_DECREF(&PlaneType);
        Py_DECREF(m);
        return nullptr;
    }
    if (PyModule_AddIntConstant(m, "POLICY_FIFO", ptsched::POLICY_FIFO) < 0 ||
        PyModule_AddIntConstant(m, "POLICY_PRIO", ptsched::POLICY_PRIO) < 0 ||
        PyModule_AddIntConstant(m, "POLICY_WDRR", ptsched::POLICY_WDRR) < 0 ||
        PyModule_AddIntConstant(m, "POLICY_RNDSTEAL",
                                ptsched::POLICY_RNDSTEAL) < 0 ||
        PyModule_AddIntConstant(m, "KIND_ANY", ptsched::KIND_ANY) < 0 ||
        PyModule_AddIntConstant(m, "KIND_PTEXEC", ptsched::KIND_PTEXEC) < 0 ||
        PyModule_AddIntConstant(m, "KIND_PTDTD", ptsched::KIND_PTDTD) < 0 ||
        PyModule_AddIntConstant(m, "KIND_EXT", ptsched::KIND_EXT) < 0 ||
        PyModule_AddIntConstant(m, "MAX_WORKERS", ptsched::MAX_WORKERS) < 0 ||
        PyModule_AddIntConstant(m, "MAX_POOLS", ptsched::MAX_POOLS) < 0 ||
        PyModule_AddIntConstant(m, "HOTQ_CAP", ptsched::HOTQ_CAP) < 0) {
        Py_DECREF(m);
        return nullptr;
    }
    return m;
}
