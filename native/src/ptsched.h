// ptsched — the native multi-pool scheduler plane (ISSUE 9).
//
// Stands where the reference's MCA scheduler family stands
// (parsec/mca/sched/sched.h:210-335, LFQ/LTQ/AP/PBQ/RND): a SHARED ready
// plane both native engines (_ptexec graphs, the _ptdtd batch lane) drain
// through instead of their private ready vectors, so N concurrent
// taskpools share the execution lanes by configurable QoS weight instead
// of whoever-inserted-last winning. Structure mirrors the reference's
// local-queues shape (hbbuffer.c + sched_local_queues_utils.h):
//
//   * per-WORKER bounded hot queues (the HBBUFF role): the owner pushes
//     and pops the back (hot/LIFO end); overflow spills to the owning
//     pool's cold structure, counted per pool;
//   * per-POOL overflow queues — a plain LIFO vector, or a max-heap once
//     any nonzero priority is pushed (the ptexec use_heap contract);
//   * cross-worker STEALING: a starved worker visits victims' hot queues
//     with try_lock only (a contended victim is skipped, never waited on)
//     and carries HALF the matching items home from the COLD end —
//     heap_split_and_steal's "related work migrates together", counted
//     per thief;
//   * weighted DEFICIT-ROUND-ROBIN arbitration across registered pools:
//     mixed pops (the DTD drain) refill from pool overflow in DRR order,
//     and next_pool()/charge() drive the same deficits for consumers that
//     must drain one pool at a time (the ptexec lane queue in
//     core/context.py) — every pool with queued work is visited within
//     one cursor cycle, so the starvation bound is structural;
//   * ADMISSION window per pool: admit()/retired() track in-flight
//     (inserted-not-completed) tasks; past the window, push/insert paths
//     report a soft-limit signal the Python side turns into a
//     bounded-blocking (or nowait-erroring) insert_task.
//
// SHARING ACROSS EXTENSIONS: _ptexec/_ptdtd/_ptsched are separate .so's
// built from this one header in one `make` invocation (native/Makefile),
// so the struct layout is identical in all of them; the live Plane is
// allocated by _ptsched and handed to the engines as a PyCapsule carrying
// the raw pointer (abi field checked first, the ptcomm_iface.h pattern).
// All plane entry points are GIL-agnostic: engines call them with the GIL
// dropped mid-walk, the comm progress thread calls push() from ingest.
//
// SINGLE-POOL FAST PATH: with one live pool and no contention a push or a
// batched pop costs one uncontended mutex acquire and vector ops on
// preallocated storage — no allocation, no arbitration walk — keeping the
// bound chain bench inside the <2% overhead contract (bench.py asserts
// `sched_plane_overhead_pct_native`).
//
// Policies (selected by --mca sched through SchedulerModule.native_policy,
// core/scheduler.py):
//   FIFO      pool overflow drains oldest-first, round-robin across pools
//   PRIO      strict priority: hot queues bypassed, per-pool max-heaps,
//             the pool with the best top priority is served first
//   WDRR      (default, lfq) hot queues + steal + weighted DRR refill
//   RNDSTEAL  WDRR structure with randomized victim/pool visit order

#ifndef PARSEC_TPU_PTSCHED_H
#define PARSEC_TPU_PTSCHED_H

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <mutex>
#include <new>
#include <vector>

#include "pthist.h"
#include "ptrace_ring.h"

// capsule name (PyCapsule_New contract; holder keeps the plane alive via
// the capsule's context ref — see ptsched.cpp plane_capsule)
#define PTSCHED_PLANE_CAPSULE "parsec_tpu.ptsched.plane"

namespace ptsched {

constexpr int ABI = 2;          // bump on any layout/semantics change
                                // (2: atomic weight + remote windows,
                                // ISSUE 11)

constexpr int MAX_WORKERS = 64;
constexpr int MAX_POOLS = 1024;
constexpr int HOTQ_CAP = 256;   // per-worker bounded hot queue (HBBUFF cap)

constexpr int POLICY_FIFO = 0;
constexpr int POLICY_PRIO = 1;
constexpr int POLICY_WDRR = 2;
constexpr int POLICY_RNDSTEAL = 3;

// pool kinds: consumers pop only their own kind (the DTD engine must
// never receive a ptexec graph's task id and vice versa)
constexpr int KIND_ANY = -1;
constexpr int KIND_PTEXEC = 0;
constexpr int KIND_PTDTD = 1;
constexpr int KIND_EXT = 2;     // plane-only harnesses (tests)

// queue-wait histogram: sampled 1-in-8 by task id, the ptexec discipline
inline bool queue_sampled(int32_t tid) { return (tid & 7) == 0; }

struct Item {
    int32_t tid;
    int32_t pool;    // plane pool handle (slot index)
    int32_t prio;
    int32_t pad_;
    int64_t t_push;  // push stamp (ns) for sched.queue_ns; 0 = unsampled
};

// max-heap on (prio, tid): among equal priorities the higher id wins —
// the exact PrioLess contract of ptexec.cpp so heap pools keep the lane's
// ordering guarantee when their ready storage moves here
struct ItemPrioLess {
    bool operator()(const Item &a, const Item &b) const {
        return a.prio < b.prio || (a.prio == b.prio && a.tid < b.tid);
    }
};

struct Pool {
    std::mutex mu;                 // guards overflow/heap/live transitions
    std::vector<Item> overflow;    // LIFO vector, max-heap once `heap`
    bool heap = false;             // sticky: set by the first nonzero prio
    bool live = false;
    int kind = KIND_EXT;
    // weight is ATOMIC (ISSUE 11): the serving fabric's reconciliation
    // loop nudges it mid-run (set_weight) while DRR refills read it
    std::atomic<int32_t> weight{1};
    int64_t window = 0;            // admission window, 0 = unlimited
    uint32_t ext_id = 0;           // caller's pool identity (diagnostics)
    int64_t deficit = 0;           // DRR credits (guarded by arb_mu)
    std::atomic<int64_t> queued{0};    // items in hot queues + overflow
    std::atomic<int64_t> inflight{0};  // admit() - retired()
    // window room RESERVED for remote inserters (ISSUE 11): credits
    // granted on the wire and not yet consumed/returned/reclaimed.
    // over_window charges it alongside inflight, so local and remote
    // admission share ONE budget per pool
    std::atomic<int64_t> remote_granted{0};
    std::atomic<int64_t> served{0};    // items popped for execution
    std::atomic<int64_t> spills{0};    // hot-queue overflow -> pool cold
    std::atomic<int64_t> stalls{0};    // admission stalls (python bumps)
};

struct HotQ {
    std::mutex mu;
    std::vector<Item> buf;         // back = hot end, front = cold end
};

struct Plane {
    int abi = ABI;
    int nworkers = 1;
    int policy = POLICY_WDRR;
    int64_t quantum = 256;         // DRR credit unit per weight point
    Pool pools[MAX_POOLS];
    HotQ hot[MAX_WORKERS];
    std::mutex reg_mu;             // registration/unregistration
    std::mutex arb_mu;             // DRR cursors + deficits
    int cursor[3] = {0, 0, 0};     // per-kind DRR cursor (ptexec/ptdtd/ext)
    std::atomic<int64_t> steals[MAX_WORKERS];   // items stolen BY worker w
    std::atomic<int64_t> steal_visits{0};       // victim queues examined
    std::atomic<int64_t> pools_registered{0};   // lifetime registrations
    std::atomic<int64_t> pools_live{0};
    std::atomic<int64_t> admission_stalls{0};
    std::atomic<int64_t> weight_adjusts{0};   // set_weight calls (ptfab)
    // plane-LIFETIME accumulators: per-pool counters reset when a freed
    // slot is re-registered, so summing them is non-monotonic — a
    // metrics counter must never go backwards
    std::atomic<int64_t> served_total{0};
    std::atomic<int64_t> spills_total{0};
    std::atomic<pthist::State<1> *> hist{nullptr};  // "queue_ns"
    std::atomic<uint32_t> rng{0x9E3779B9u};

    Plane(int nw, int pol, int64_t q) {
        nworkers = nw < 1 ? 1 : (nw > MAX_WORKERS ? MAX_WORKERS : nw);
        policy = pol;
        quantum = q > 0 ? q : 256;
        for (int w = 0; w < MAX_WORKERS; w++)
            steals[w].store(0, std::memory_order_relaxed);
        for (int w = 0; w < nworkers; w++)
            hot[w].buf.reserve(HOTQ_CAP);
    }
    ~Plane() { delete hist.load(std::memory_order_acquire); }

    inline uint32_t xrand() {
        // xorshift32 — victim/pool visit order for RNDSTEAL; collisions
        // are harmless (it only biases the walk order)
        uint32_t x = rng.load(std::memory_order_relaxed);
        x ^= x << 13; x ^= x >> 17; x ^= x << 5;
        rng.store(x, std::memory_order_relaxed);
        return x;
    }

    inline pthist::State<1> *hist_armed() {
        pthist::State<1> *hs = hist.load(std::memory_order_acquire);
        if (hs && !hs->enabled.load(std::memory_order_relaxed)) hs = nullptr;
        return hs;
    }

    // --------------------------------------------------------- registration
    // -> pool handle (slot index), or -1 when the table is full. Slots are
    // static storage and reusable after unregister; a handle never dangles.
    int pool_register(uint32_t ext_id, int kind, int32_t weight,
                      int64_t window) {
        std::lock_guard<std::mutex> rl(reg_mu);
        for (int i = 0; i < MAX_POOLS; i++) {
            Pool &p = pools[i];
            bool claimed = false;
            {
                std::lock_guard<std::mutex> pl(p.mu);
                if (!p.live) {
                    p.overflow.clear();
                    p.heap = (policy == POLICY_PRIO);
                    p.kind = kind;
                    p.weight.store(weight > 0 ? weight : 1,
                                   std::memory_order_relaxed);
                    p.window = window > 0 ? window : 0;
                    p.ext_id = ext_id;
                    p.queued.store(0, std::memory_order_relaxed);
                    p.inflight.store(0, std::memory_order_relaxed);
                    p.remote_granted.store(0, std::memory_order_relaxed);
                    p.served.store(0, std::memory_order_relaxed);
                    p.spills.store(0, std::memory_order_relaxed);
                    p.stalls.store(0, std::memory_order_relaxed);
                    p.live = true;
                    claimed = true;
                }
            }
            if (!claimed) continue;
            {
                // deficit reset AFTER p.mu drops: the arbitration lock
                // nests INSIDE p.mu's scope here while refill_drr holds
                // arb_mu across take_overflow's p.mu — taking them in
                // both orders was an ABBA deadlock a register racing a
                // mixed-kind pop could hit (found by the churn test
                // wedging the full suite under load; whichever thread
                // deadlocked held the GIL, freezing the process). A pop
                // reading the pre-reset deficit in the window costs one
                // WDRR credit blip on a just-registered pool, nothing
                // more — deficit is advisory fairness state.
                std::lock_guard<std::mutex> al(arb_mu);
                p.deficit = 0;
            }
            pools_registered.fetch_add(1, std::memory_order_relaxed);
            pools_live.fetch_add(1, std::memory_order_relaxed);
            return i;
        }
        return -1;
    }

    // Drop a pool: sweep its straggler items out of every hot queue, clear
    // its overflow, free the slot. Safe mid-run: slots are static storage,
    // so a pop racing the sweep at worst returns an item for a pool that
    // just died — the consumer side (engine/harness) tolerates that the
    // same way ptcomm tolerates late frames. Normal flow unregisters only
    // after the pool quiesced (queued == 0, inflight == 0).
    void pool_unregister(int h) {
        if (h < 0 || h >= MAX_POOLS) return;
        pool_clear(h);           // ONE home for the zombie-item sweep
        Pool &p = pools[h];
        std::lock_guard<std::mutex> pl(p.mu);
        if (p.live) {
            p.live = false;
            pools_live.fetch_sub(1, std::memory_order_relaxed);
        }
    }

    // Drain EVERY queued item of pool h into `out` with BLOCKING locks —
    // the unbind migration path: the regular pop's steal uses try_lock
    // and skips contended victims, which would silently drop their items
    // to the unregister sweep. Cold path; correctness over latency.
    void pool_drain_all(int h, std::vector<int32_t> &out) {
        if (h < 0 || h >= MAX_POOLS) return;
        for (int w = 0; w < nworkers; w++) {
            std::lock_guard<std::mutex> hl(hot[w].mu);
            std::vector<Item> &b = hot[w].buf;
            size_t o = 0;
            for (size_t i = 0; i < b.size(); i++) {
                if (b[i].pool == h)
                    out.push_back(b[i].tid);
                else
                    b[o++] = b[i];
            }
            b.resize(o);
        }
        Pool &p = pools[h];
        std::lock_guard<std::mutex> pl(p.mu);
        for (const Item &it : p.overflow) out.push_back(it.tid);
        p.overflow.clear();
        p.queued.store(0, std::memory_order_relaxed);
    }

    // Flush a pool's queued items (hot queues + overflow) without freeing
    // the slot — the graph replay (reset) path: stale items from an
    // abandoned run must not resurface in the rewound graph.
    void pool_clear(int h) {
        if (h < 0 || h >= MAX_POOLS) return;
        Pool &p = pools[h];
        for (int w = 0; w < nworkers; w++) {
            std::lock_guard<std::mutex> hl(hot[w].mu);
            std::vector<Item> &b = hot[w].buf;
            size_t o = 0;
            for (size_t i = 0; i < b.size(); i++)
                if (b[i].pool != h) b[o++] = b[i];
            b.resize(o);
        }
        std::lock_guard<std::mutex> pl(p.mu);
        p.overflow.clear();
        p.queued.store(0, std::memory_order_relaxed);
        p.inflight.store(0, std::memory_order_relaxed);
    }

    // ------------------------------------------------------------ admission
    inline void admit(int h, int64_t n) {
        if (h >= 0) pools[h].inflight.fetch_add(n, std::memory_order_relaxed);
    }
    inline void retired(int h, int64_t n) {
        if (h >= 0) pools[h].inflight.fetch_sub(n, std::memory_order_relaxed);
    }
    inline int64_t inflight_of(int h) {
        return h < 0 ? 0 : pools[h].inflight.load(std::memory_order_relaxed);
    }
    inline int64_t charge_of(Pool &p) {
        // total window charge: local in-flight + room reserved for
        // remote inserters (the ISSUE 11 shared-budget contract)
        return p.inflight.load(std::memory_order_relaxed) +
               p.remote_granted.load(std::memory_order_relaxed);
    }
    inline bool over_window(int h) {
        if (h < 0) return false;
        Pool &p = pools[h];
        return p.window > 0 && charge_of(p) > p.window;
    }

    // ---------------------------------------------------- remote windows
    // reserve/release window room for credits granted to remote
    // inserters (ISSUE 11). The fabric reserves BEFORE a wire grant and
    // releases as granted work arrives (admit() then carries it as
    // inflight), as unspent credits return, or at peer-death reclaim —
    // the reservation can therefore never leak past those three paths.
    inline void remote_grant(int h, int64_t n) {
        if (h >= 0)
            pools[h].remote_granted.fetch_add(n, std::memory_order_relaxed);
    }
    inline void remote_release(int h, int64_t n) {
        if (h < 0) return;
        // floor at 0: a release racing a reclaim must not go negative
        // (advisory accounting, same discipline as the DRR deficit)
        Pool &p = pools[h];
        int64_t cur = p.remote_granted.load(std::memory_order_relaxed);
        while (cur > 0) {
            int64_t next = cur > n ? cur - n : 0;
            if (p.remote_granted.compare_exchange_weak(
                    cur, next, std::memory_order_relaxed,
                    std::memory_order_relaxed))
                break;
        }
    }
    inline int64_t remote_granted_of(int h) {
        return h < 0 ? 0
                     : pools[h].remote_granted.load(
                           std::memory_order_relaxed);
    }
    // window room still grantable: window - inflight - remote_granted,
    // or -1 for an unlimited pool (window == 0)
    inline int64_t headroom_of(int h) {
        if (h < 0) return 0;
        Pool &p = pools[h];
        if (p.window <= 0) return -1;
        int64_t room = p.window - charge_of(p);
        return room > 0 ? room : 0;
    }

    // mid-run QoS nudge (ISSUE 11): the reconciliation loop's capsule
    // entry. Weight binds at the NEXT DRR round top-up; the in-flight
    // deficit is untouched (advisory fairness state, like register's)
    void set_weight(int h, int32_t w) {
        if (h < 0 || h >= MAX_POOLS) return;
        pools[h].weight.store(w > 0 ? w : 1, std::memory_order_relaxed);
        weight_adjusts.fetch_add(1, std::memory_order_relaxed);
    }

    // ----------------------------------------------------------------- push
    // Push n ready items for pool h. `worker` >= 0 routes through that
    // worker's hot queue (overflow spills to the pool, counted); heap
    // pools and anonymous producers (worker < 0: the comm ingest thread,
    // Python harnesses) go straight to the pool's cold structure.
    // Returns true when the pool is over its admission window (the soft
    // backpressure signal — purely advisory, the push always lands).
    bool push(int h, int worker, const int32_t *tids, const int32_t *prios,
              int n) {
        if (h < 0 || n <= 0) return false;
        Pool &p = pools[h];
        pthist::State<1> *hs = hist_armed();
        int64_t now = hs ? ptrace_ring::now_ns() : 0;
        bool to_heap = p.heap;
        if (!to_heap && prios) {
            for (int i = 0; i < n; i++)
                if (prios[i] != 0) { to_heap = true; break; }
            if (to_heap) {
                // first prioritized push: migrate the pool to heap order
                std::lock_guard<std::mutex> pl(p.mu);
                if (!p.heap) {
                    std::make_heap(p.overflow.begin(), p.overflow.end(),
                                   ItemPrioLess{});
                    p.heap = true;
                }
            }
        }
        int taken = 0;
        bool tried_hot = false;
        if (!to_heap && worker >= 0 && worker < nworkers) {
            tried_hot = true;
            HotQ &q = hot[worker];
            std::lock_guard<std::mutex> hl(q.mu);
            int room = HOTQ_CAP - (int)q.buf.size();
            taken = room < n ? (room > 0 ? room : 0) : n;
            for (int i = 0; i < taken; i++)
                q.buf.push_back(Item{
                    tids[i], h, prios ? prios[i] : 0, 0,
                    (now && queue_sampled(tids[i])) ? now : 0});
        }
        if (taken < n) {
            std::lock_guard<std::mutex> pl(p.mu);
            for (int i = taken; i < n; i++) {
                p.overflow.push_back(Item{
                    tids[i], h, prios ? prios[i] : 0, 0,
                    (now && queue_sampled(tids[i])) ? now : 0});
                if (p.heap)
                    std::push_heap(p.overflow.begin(), p.overflow.end(),
                                   ItemPrioLess{});
            }
            if (tried_hot) { // a hot-queue push that spilled — including
                             // the fully-saturated case (taken == 0),
                             // exactly the regime the counter signals
                p.spills.fetch_add(n - taken, std::memory_order_relaxed);
                spills_total.fetch_add(n - taken,
                                       std::memory_order_relaxed);
            }
        }
        p.queued.fetch_add(n, std::memory_order_relaxed);
        return p.window > 0 &&
               p.inflight.load(std::memory_order_relaxed) > p.window;
    }

    // ------------------------------------------------------------ pop
    // Pop up to cap items for `worker`: own hot queue first (hot end),
    // then pool overflow (DRR across pools for kind-filtered pops, the
    // named pool for pool-filtered ones), then steal-half from victims'
    // cold ends. `pool_filter` >= 0 restricts to one pool (the ptexec
    // graph's view); otherwise `kind` restricts to that engine's pools.
    int pop(int worker, int kind, int pool_filter, Item *out, int cap) {
        if (cap <= 0) return 0;
        int n = 0;
        int w = (worker >= 0 && worker < nworkers) ? worker : 0;
        // 1. own hot queue, hot end first: the matching tail comes off as
        // ONE block (the single-pool common case never pays per-item
        // erases); deeper non-contiguous matches take the slow scan
        {
            HotQ &q = hot[w];
            std::lock_guard<std::mutex> hl(q.mu);
            std::vector<Item> &b = q.buf;
            size_t sz = b.size();
            size_t take = 0;
            while (take < sz && n + (int)take < cap &&
                   match(b[sz - 1 - take], kind, pool_filter))
                take++;
            for (size_t t = 0; t < take; t++) out[n++] = b[sz - 1 - t];
            b.resize(sz - take);
            if (n < cap && !b.empty()) {
                for (size_t i = b.size(); i-- > 0 && n < cap;) {
                    if (!match(b[i], kind, pool_filter)) continue;
                    out[n++] = b[i];
                    b.erase(b.begin() + (ptrdiff_t)i);
                }
            }
        }
        // 2. pool overflow refill
        if (n < cap) {
            if (pool_filter >= 0)
                n += take_overflow(pools[pool_filter], pool_filter,
                                   out + n, cap - n);
            else if (n == 0)
                n += refill_drr(kind, out, cap);
        }
        // 3. steal from peers' cold ends
        if (n == 0 && nworkers > 1)
            n = steal(w, kind, pool_filter, out, cap);
        if (n) account_pops(out, n);
        return n;
    }

    // Specialized single-pool pop (the ptexec lane's view): emits RAW
    // task ids straight into the caller's buffer — no Item copies, no
    // second extraction pass, accounting batched to 2 atomics per call.
    // This is the other half of the single-pool <2% overhead contract:
    // the plane-bound chain walk pays (bulk tail take + one push) per
    // ~256 tasks, the same order of work as the private vector did.
    int pop_pool(int h, int worker, int32_t *tids, int cap) {
        if (cap <= 0 || h < 0) return 0;
        Pool &p = pools[h];
        pthist::State<1> *hs = hist_armed();
        int64_t now = hs ? ptrace_ring::now_ns() : 0;
        int n = 0;
        int w = (worker >= 0 && worker < nworkers) ? worker : 0;
        {
            HotQ &q = hot[w];
            std::lock_guard<std::mutex> hl(q.mu);
            std::vector<Item> &b = q.buf;
            size_t sz = b.size();
            size_t take = 0;
            while (take < sz && (int)take < cap &&
                   b[sz - 1 - take].pool == h)
                take++;
            for (size_t t = 0; t < take; t++) {
                const Item &it = b[sz - 1 - t];
                if (now && it.t_push > 0) hs->h[0].add(now - it.t_push);
                tids[n++] = it.tid;
            }
            b.resize(sz - take);
            if (n < cap && !b.empty()) {
                for (size_t i = b.size(); i-- > 0 && n < cap;) {
                    if (b[i].pool != h) continue;
                    if (now && b[i].t_push > 0)
                        hs->h[0].add(now - b[i].t_push);
                    tids[n++] = b[i].tid;
                    b.erase(b.begin() + (ptrdiff_t)i);
                }
            }
        }
        if (n < cap) {
            std::lock_guard<std::mutex> pl(p.mu);
            while (n < cap && !p.overflow.empty()) {
                if (p.heap)
                    std::pop_heap(p.overflow.begin(), p.overflow.end(),
                                  ItemPrioLess{});
                else if (policy == POLICY_FIFO) {
                    const Item &it = p.overflow.front();
                    if (now && it.t_push > 0)
                        hs->h[0].add(now - it.t_push);
                    tids[n++] = it.tid;
                    p.overflow.erase(p.overflow.begin());
                    continue;
                }
                const Item &it = p.overflow.back();
                if (now && it.t_push > 0) hs->h[0].add(now - it.t_push);
                tids[n++] = it.tid;
                p.overflow.pop_back();
            }
        }
        if (n == 0 && nworkers > 1) {
            Item loot[HOTQ_CAP];
            int got = steal(w, KIND_ANY, h, loot,
                            cap < HOTQ_CAP ? cap : HOTQ_CAP);
            for (int i = 0; i < got; i++) {
                if (now && loot[i].t_push > 0)
                    hs->h[0].add(now - loot[i].t_push);
                tids[n++] = loot[i].tid;
            }
        }
        if (n) {
            p.queued.fetch_sub(n, std::memory_order_relaxed);
            p.served.fetch_add(n, std::memory_order_relaxed);
            served_total.fetch_add(n, std::memory_order_relaxed);
        }
        return n;
    }

    // ----------------------------------------------------- DRR arbitration
    // Pick the next pool of `kind` holding queued work, topping up its
    // deficit (weight * quantum per visit); *quantum_out receives the
    // credits the caller may spend before charge()-ing back. -1 = no
    // queued pool. The cursor advances every call, so every queued pool
    // is visited within one cycle — the starvation bound.
    int next_pool(int kind, int64_t *quantum_out) {
        int k = kind_slot(kind);
        std::lock_guard<std::mutex> al(arb_mu);
        int start = cursor[k];
        for (int step = 0; step < MAX_POOLS; step++) {
            int i = (start + step) % MAX_POOLS;
            Pool &p = pools[i];
            if (!p.live || (kind != KIND_ANY && p.kind != kind)) continue;
            if (p.queued.load(std::memory_order_relaxed) <= 0) {
                p.deficit = 0;    // an empty pool carries no credit over
                continue;
            }
            cursor[k] = (i + 1) % MAX_POOLS;
            p.deficit += (int64_t)p.weight.load(std::memory_order_relaxed) *
                         quantum;
            if (quantum_out) *quantum_out = p.deficit;
            return i;
        }
        return -1;
    }

    void charge(int h, int64_t n) {
        if (h < 0 || h >= MAX_POOLS) return;
        std::lock_guard<std::mutex> al(arb_mu);
        Pool &p = pools[h];
        p.deficit -= n;
        if (p.deficit < 0 ||
            p.queued.load(std::memory_order_relaxed) <= 0)
            p.deficit = 0;
    }

    int64_t deficit_of(int h) {
        if (h < 0 || h >= MAX_POOLS) return 0;
        std::lock_guard<std::mutex> al(arb_mu);
        return pools[h].deficit;
    }

    // ------------------------------------------------------------- queries
    inline int64_t queued_of(int h) {
        return h < 0 ? 0 : pools[h].queued.load(std::memory_order_relaxed);
    }
    int64_t queued_kind(int kind) {
        int64_t total = 0;
        for (int i = 0; i < MAX_POOLS; i++) {
            Pool &p = pools[i];
            if (!p.live || (kind != KIND_ANY && p.kind != kind)) continue;
            total += p.queued.load(std::memory_order_relaxed);
        }
        return total;
    }

  private:
    static inline int kind_slot(int kind) {
        return kind == KIND_PTEXEC ? 0 : (kind == KIND_PTDTD ? 1 : 2);
    }
    inline bool match(const Item &it, int kind, int pool_filter) const {
        if (pool_filter >= 0) return it.pool == pool_filter;
        if (kind == KIND_ANY) return true;
        return pools[it.pool].kind == kind && pools[it.pool].live;
    }

    // take up to cap items from one pool's overflow (heap top; LIFO back;
    // or the FRONT under FIFO policy — oldest-first, batch-amortized)
    int take_overflow(Pool &p, int h, Item *out, int cap) {
        (void)h;
        std::lock_guard<std::mutex> pl(p.mu);
        int n = 0;
        if (policy == POLICY_FIFO && !p.heap) {
            int k = (int)p.overflow.size() < cap ? (int)p.overflow.size()
                                                 : cap;
            for (; n < k; n++) out[n] = p.overflow[(size_t)n];
            p.overflow.erase(p.overflow.begin(),
                             p.overflow.begin() + (ptrdiff_t)n);
            return n;
        }
        while (n < cap && !p.overflow.empty()) {
            if (p.heap)
                std::pop_heap(p.overflow.begin(), p.overflow.end(),
                              ItemPrioLess{});
            out[n++] = p.overflow.back();
            p.overflow.pop_back();
        }
        return n;
    }

    // mixed refill honoring the policy: WDRR spends deficits, FIFO/RND
    // round-robin with unit weight, PRIO serves the best top priority.
    // WDRR is CLASSIC deficit-round-robin across pop calls: the cursor
    // STAYS on a pool until its per-round credit (weight * quantum) is
    // spent or its queue drains — a weight-2 pool is served ~2x a
    // weight-1 pool even though each pop call fills from one pool
    // (advancing every call would degrade to unweighted alternation).
    int refill_drr(int kind, Item *out, int cap) {
        if (policy == POLICY_PRIO) return refill_prio(kind, out, cap);
        const bool wdrr = policy == POLICY_WDRR;
        int k = kind_slot(kind);
        int n = 0;
        std::unique_lock<std::mutex> al(arb_mu);
        if (policy == POLICY_RNDSTEAL)
            cursor[k] = (int)(xrand() % MAX_POOLS);
        int i = cursor[k] % MAX_POOLS;
        for (int step = 0; step < MAX_POOLS && n < cap;) {
            Pool &p = pools[i];
            if (!p.live || (kind != KIND_ANY && p.kind != kind) ||
                p.queued.load(std::memory_order_relaxed) <= 0) {
                if (p.live) p.deficit = 0;   // no credit carries while idle
                i = (i + 1) % MAX_POOLS;
                step++;
                continue;
            }
            if (wdrr && p.deficit <= 0)      // round top-up, once per visit
                p.deficit +=
                    (int64_t)p.weight.load(std::memory_order_relaxed) *
                    quantum;
            int64_t credit = wdrr ? p.deficit : quantum;
            int want = (int)((int64_t)(cap - n) < credit
                                 ? (int64_t)(cap - n) : credit);
            int got = take_overflow(p, i, out + n, want);
            n += got;
            if (wdrr) {
                p.deficit -= got;
                if (got < want) p.deficit = 0;   // overflow drained
            }
            if (wdrr && p.deficit > 0 && got == want && n >= cap)
                break;                       // credit left: STAY for the
                                             // next pop call
            i = (i + 1) % MAX_POOLS;
            step++;
        }
        cursor[k] = i;
        return n;
    }

    int refill_prio(int kind, Item *out, int cap) {
        // serve the pool whose top priority is best (ties by slot
        // order), re-picking until the batch fills or every pool drains
        int n = 0;
        while (n < cap) {
            int best = -1;
            int32_t best_prio = 0;
            for (int i = 0; i < MAX_POOLS; i++) {
                Pool &p = pools[i];
                if (!p.live || (kind != KIND_ANY && p.kind != kind))
                    continue;
                if (p.queued.load(std::memory_order_relaxed) <= 0) continue;
                std::lock_guard<std::mutex> pl(p.mu);
                if (p.overflow.empty()) continue;
                int32_t top = p.heap ? p.overflow.front().prio
                                     : p.overflow.back().prio;
                if (best < 0 || top > best_prio) {
                    best = i;
                    best_prio = top;
                }
            }
            if (best < 0) break;
            int got = take_overflow(pools[best], best, out + n, cap - n);
            if (!got) break;
            n += got;
        }
        return n;
    }

    // steal-half from victims' cold ends; try_lock only (a busy victim is
    // skipped); surplus beyond cap lands in the thief's own hot queue
    int steal(int thief, int kind, int pool_filter, Item *out, int cap) {
        std::vector<Item> loot;
        uint32_t start = (policy == POLICY_RNDSTEAL)
                             ? xrand() % (uint32_t)nworkers
                             : (uint32_t)(thief + 1);
        for (int d = 0; d < nworkers && loot.empty(); d++) {
            int v = (int)((start + (uint32_t)d) % (uint32_t)nworkers);
            if (v == thief) continue;
            HotQ &q = hot[v];
            if (!q.mu.try_lock()) continue;
            steal_visits.fetch_add(1, std::memory_order_relaxed);
            std::vector<Item> &b = q.buf;
            int nmatch = 0;
            for (const Item &it : b)
                if (match(it, kind, pool_filter)) nmatch++;
            int want = (nmatch + 1) / 2;    // steal-half, at least 1
            size_t o = 0;
            for (size_t i = 0; i < b.size(); i++) {
                // cold end = front: the first `want` matches are carried off
                if ((int)loot.size() < want &&
                    match(b[i], kind, pool_filter)) {
                    loot.push_back(b[i]);
                } else {
                    b[o++] = b[i];
                }
            }
            b.resize(o);
            q.mu.unlock();
        }
        if (loot.empty()) return 0;
        steals[thief].fetch_add((int64_t)loot.size(),
                                std::memory_order_relaxed);
        int n = (int)loot.size() < cap ? (int)loot.size() : cap;
        for (int i = 0; i < n; i++) out[i] = loot[(size_t)i];
        if ((int)loot.size() > n) {
            std::lock_guard<std::mutex> hl(hot[thief].mu);
            for (size_t i = (size_t)n; i < loot.size(); i++)
                hot[thief].buf.push_back(loot[i]);
        }
        return n;
    }

    void account_pops(const Item *out, int n) {
        pthist::State<1> *hs = hist_armed();
        int64_t now = hs ? ptrace_ring::now_ns() : 0;
        // same-pool runs account with ONE pair of atomics (a batch is
        // almost always one pool): 2 RMWs per ~256 tasks, not per task —
        // the single-pool fast path's half of the <2% overhead contract
        int i = 0;
        while (i < n) {
            int j = i;
            const int32_t p = out[i].pool;
            while (j < n && out[j].pool == p) {
                if (now && out[j].t_push > 0)
                    hs->h[0].add(now - out[j].t_push);
                j++;
            }
            pools[p].queued.fetch_sub(j - i, std::memory_order_relaxed);
            pools[p].served.fetch_add(j - i, std::memory_order_relaxed);
            served_total.fetch_add(j - i, std::memory_order_relaxed);
            i = j;
        }
    }
};

// resolve + abi-check a plane capsule; sets a Python error on failure
inline Plane *plane_from_capsule(PyObject *cap) {
    Plane *pl = static_cast<Plane *>(
        PyCapsule_GetPointer(cap, PTSCHED_PLANE_CAPSULE));
    if (!pl) return nullptr;
    if (pl->abi != ABI) {
        PyErr_SetString(PyExc_RuntimeError, "ptsched ABI mismatch");
        return nullptr;
    }
    return pl;
}

}  // namespace ptsched

#endif  // PARSEC_TPU_PTSCHED_H
