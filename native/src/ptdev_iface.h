// C-level contract between the native engines (_ptexec, _ptdtd) and the
// native device lane (_ptdev) — the fourth separate CPython extension.
//
// Same linkage model as ptcomm_iface.h: the artifacts share no symbols
// and meet at runtime through PyCapsules carrying plain-C vtables. Both
// directions of the device hot path are GIL-free:
//
//   engine -> device  (PtDevSubmitVtbl): a release sweep (or a comm
//     ingest) discovering a newly-ready DEVICE-BODIED task enqueues it
//     onto the device lane's lock-free MPSC pending queue — one function
//     call, no GIL, never blocks. The task does NOT enter the engine's
//     ready structure (a device chore no longer makes the pool
//     ineligible; it surfaces here instead — the rsurf pattern of the
//     comm lane applied to the device plane).
//
//   device -> engine  (PtDevRetireVtbl): the device manager thread
//     observed a dispatched task's completion events (jax.Array
//     is_ready, the cudaEventQuery of device_gpu.c:2593) and lands the
//     completion straight into the engine's release walk — successor
//     decrements, slot retires and ready pushes all run without the GIL,
//     exactly like a local CPU retire (the kernel_epilog ->
//     complete_task_execution edge of device_gpu.c:3179, funneled).
//
// Lifetime rules (enforced by parsec_tpu/device/native.py, which owns
// both ends): the Lane pins the engine object with a Python reference
// for the bind window (bind_pool INCREFs, unbind_pool DECREFs), and a
// bound engine must be unbound before the Lane is destroyed. Vtables
// are POD copied by value; `dev`/`obj` are borrowed pointers whose
// validity is exactly the bind window.

#ifndef PARSEC_TPU_PTDEV_IFACE_H
#define PARSEC_TPU_PTDEV_IFACE_H

#include <stdint.h>

// bump on any layout/semantics change; both sides check before use
#define PTDEV_ABI 1

// capsule names (PyCapsule_New/Import contract)
#define PTDEV_SUBMIT_CAPSULE "parsec_tpu.ptdev.submit_vtbl"
#define PTDEV_RETIRE_CAPSULE "parsec_tpu.ptdev.retire_vtbl"

extern "C" {

// device-lane entry point the engine release sweeps call (NO GIL):
typedef struct PtDevSubmitVtbl {
    int abi;
    void *dev;  // the ptdev Lane
    // enqueue one newly-ready device-bodied task `tid` of pool `pool`
    // onto the lane's pending queue; never blocks, never takes the GIL
    void (*submit)(void *dev, uint32_t pool, int32_t tid);
} PtDevSubmitVtbl;

// engine-side entry point the device manager thread calls (NO GIL):
typedef struct PtDevRetireVtbl {
    int abi;
    void *obj;  // the engine object (ptexec Graph / ptdtd Engine)
    // task `tid` finished on the device and its outputs already landed in
    // the Python-owned slots (the manager's poll callback lands them
    // under the GIL BEFORE this is called): run the release walk
    void (*retire)(void *obj, int32_t tid);
} PtDevRetireVtbl;

}  // extern "C"

#endif  // PARSEC_TPU_PTDEV_IFACE_H
