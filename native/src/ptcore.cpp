// parsec_tpu native core: hot runtime structures in C++.
//
// Stands where the reference's C substrate stands (parsec/class/hash_table.c,
// parsec/class/lifo.c, parsec/utils/zone_malloc.c, and the dependency update
// path parsec_update_deps_with_mask, parsec/parsec.c:1657): the Python layer
// binds these via ctypes and falls back to pure-Python when the library is
// unavailable.
//
// Exposed C ABI (see parsec_tpu/native.py):
//   dependency table  — concurrent open-addressing map from small int64[]
//                       keys to a satisfied mask/counter; update returns
//                       whether the task just became ready (goal reached),
//                       erasing the entry exactly once.
//   zone allocator    — first-fit, unit-granular, coalescing free list.
//   work deque        — mutex-protected intrusive deque of uint64 handles
//                       (push/pop front/back for LIFO/FIFO/steal policies).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <new>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// dependency table
// ---------------------------------------------------------------------------

static const int PT_KEY_MAX = 16;   // matches MAX_LOCAL_COUNT in the DSLs

struct pt_dep_entry {
    int64_t key[PT_KEY_MAX];
    int32_t klen;       // -1 = empty, -2 = tombstone
    int64_t value;
};

struct pt_dep_table {
    std::vector<pt_dep_entry> slots;
    std::mutex lock;      // one mutex: probe sequences must be atomic, and
                          // growth rehashes in place (striping would race)
    int64_t used{0};      // live entries
    int64_t filled{0};    // live + tombstones (load factor driver)
    uint64_t mask;

    explicit pt_dep_table(size_t cap) : slots(cap), mask(cap - 1) {
        for (auto &e : slots) e.klen = -1;
    }
};

static inline uint64_t pt_hash_key(const int64_t *key, int32_t klen) {
    // FNV-1a over the raw key words; bucket choice only (compares are exact)
    uint64_t h = 1469598103934665603ull;
    for (int32_t i = 0; i < klen; i++) {
        uint64_t w = (uint64_t)key[i];
        for (int b = 0; b < 8; b++) {
            h ^= (w >> (8 * b)) & 0xff;
            h *= 1099511628211ull;
        }
    }
    return h;
}

void *pt_dep_table_create(uint64_t capacity_pow2) {
    size_t cap = 1;
    while (cap < capacity_pow2) cap <<= 1;
    return new (std::nothrow) pt_dep_table(cap);
}

void pt_dep_table_destroy(void *t) {
    delete static_cast<pt_dep_table *>(t);
}

int64_t pt_dep_table_size(void *tv) {
    auto *t = static_cast<pt_dep_table *>(tv);
    std::lock_guard<std::mutex> g(t->lock);
    return t->used;
}

// locked helpers -------------------------------------------------------------

static void pt_dep_rehash(pt_dep_table *t, size_t newcap) {
    std::vector<pt_dep_entry> old;
    old.swap(t->slots);
    t->slots.assign(newcap, pt_dep_entry{});
    for (auto &e : t->slots) e.klen = -1;
    t->mask = newcap - 1;
    t->filled = 0;
    for (auto &e : old) {
        if (e.klen < 0) continue;
        uint64_t idx = pt_hash_key(e.key, e.klen) & t->mask;
        while (t->slots[idx].klen != -1) idx = (idx + 1) & t->mask;
        t->slots[idx] = e;
        t->filled++;
    }
}

// mode 0: OR contribution into a mask; mode 1: add (counter).
// Returns 1 when value reached `goal` (entry retired), else 0. The whole
// update is atomic: the "becomes ready exactly once" guarantee of
// parsec_update_deps_with_mask.
int32_t pt_dep_table_update(void *tv, const int64_t *key, int32_t klen,
                            int64_t contribution, int64_t goal, int32_t mode) {
    auto *t = static_cast<pt_dep_table *>(tv);
    if (klen > PT_KEY_MAX) return -1;
    uint64_t h = pt_hash_key(key, klen);
    std::lock_guard<std::mutex> g(t->lock);
    if ((uint64_t)t->filled * 4 >= (t->mask + 1) * 3)   // load > 0.75: grow
        pt_dep_rehash(t, (t->mask + 1) * 2);
    uint64_t idx = h & t->mask;
    uint64_t first_tomb = (uint64_t)-1;
    for (uint64_t probe = 0; probe <= t->mask; probe++, idx = (idx + 1) & t->mask) {
        pt_dep_entry &e = t->slots[idx];
        if (e.klen == -1) {  // empty: insert here (or at first tombstone)
            uint64_t at = (first_tomb != (uint64_t)-1) ? first_tomb : idx;
            pt_dep_entry &ne = t->slots[at];
            if (contribution == goal) return 1;   // single-dep: never stored
            ne.klen = klen;
            std::memcpy(ne.key, key, sizeof(int64_t) * klen);
            ne.value = contribution;
            t->used++;
            if (at == idx) t->filled++;           // tombstone reuse keeps filled
            return 0;
        }
        if (e.klen == -2) {
            if (first_tomb == (uint64_t)-1) first_tomb = idx;
            continue;
        }
        if (e.klen == klen && 0 == std::memcmp(e.key, key, sizeof(int64_t) * klen)) {
            e.value = (mode == 0) ? (e.value | contribution)
                                  : (e.value + contribution);
            if (e.value == goal) {
                e.klen = -2;          // retire: task launches exactly once
                t->used--;
                return 1;
            }
            return 0;
        }
    }
    return -2;  // table full (cannot happen after growth)
}

int64_t pt_dep_table_get(void *tv, const int64_t *key, int32_t klen) {
    auto *t = static_cast<pt_dep_table *>(tv);
    uint64_t h = pt_hash_key(key, klen);
    std::lock_guard<std::mutex> g(t->lock);
    uint64_t idx = h & t->mask;
    for (uint64_t probe = 0; probe <= t->mask; probe++, idx = (idx + 1) & t->mask) {
        pt_dep_entry &e = t->slots[idx];
        if (e.klen == -1) return 0;
        if (e.klen == klen && 0 == std::memcmp(e.key, key, sizeof(int64_t) * klen))
            return e.value;
    }
    return 0;
}

// ---------------------------------------------------------------------------
// zone allocator (ref: parsec/utils/zone_malloc.c)
// ---------------------------------------------------------------------------

struct pt_zone {
    std::map<int64_t, int64_t> free_ranges;  // start_unit -> nb_units
    std::mutex lock;
    int64_t unit;
    int64_t total_units;
    int64_t in_use{0};
    int64_t hwm{0};
};

void *pt_zone_create(int64_t total_bytes, int64_t unit) {
    auto *z = new (std::nothrow) pt_zone();
    if (!z) return nullptr;
    z->unit = unit > 0 ? unit : (1 << 20);
    z->total_units = total_bytes / z->unit;
    if (z->total_units < 1) z->total_units = 1;
    z->free_ranges[0] = z->total_units;
    return z;
}

void pt_zone_destroy(void *zv) { delete static_cast<pt_zone *>(zv); }

// returns byte offset, or -1 when no hole fits
int64_t pt_zone_alloc(void *zv, int64_t nbytes) {
    auto *z = static_cast<pt_zone *>(zv);
    int64_t need = (nbytes + z->unit - 1) / z->unit;
    if (need < 1) need = 1;
    std::lock_guard<std::mutex> g(z->lock);
    for (auto it = z->free_ranges.begin(); it != z->free_ranges.end(); ++it) {
        if (it->second >= need) {
            int64_t start = it->first;
            int64_t rest = it->second - need;
            z->free_ranges.erase(it);
            if (rest > 0) z->free_ranges[start + need] = rest;
            z->in_use += need;
            if (z->in_use > z->hwm) z->hwm = z->in_use;
            return start * z->unit;
        }
    }
    return -1;
}

void pt_zone_free(void *zv, int64_t offset, int64_t nbytes) {
    auto *z = static_cast<pt_zone *>(zv);
    int64_t start = offset / z->unit;
    int64_t size = (nbytes + z->unit - 1) / z->unit;
    if (size < 1) size = 1;
    std::lock_guard<std::mutex> g(z->lock);
    z->in_use -= size;
    auto it = z->free_ranges.emplace(start, size).first;
    // coalesce with next
    auto nxt = std::next(it);
    if (nxt != z->free_ranges.end() && it->first + it->second == nxt->first) {
        it->second += nxt->second;
        z->free_ranges.erase(nxt);
    }
    // coalesce with prev
    if (it != z->free_ranges.begin()) {
        auto prv = std::prev(it);
        if (prv->first + prv->second == it->first) {
            prv->second += it->second;
            z->free_ranges.erase(it);
        }
    }
}

void pt_zone_stats(void *zv, int64_t *out4) {
    auto *z = static_cast<pt_zone *>(zv);
    std::lock_guard<std::mutex> g(z->lock);
    int64_t free_units = 0, largest = 0;
    for (auto &kv : z->free_ranges) {
        free_units += kv.second;
        if (kv.second > largest) largest = kv.second;
    }
    out4[0] = free_units * z->unit;
    out4[1] = z->in_use * z->unit;
    out4[2] = z->hwm * z->unit;
    out4[3] = largest * z->unit;
}

}  // extern "C"
