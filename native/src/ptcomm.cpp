// parsec_tpu._ptcomm — the native communication lane (L3 in C).
//
// Stands where the reference's funneled MPI backend stands
// (parsec/remote_dep_mpi.c + parsec_comm_engine.h): ONE progress thread
// owns every wire — it multiplexes the cross-process mesh (TCP sockets
// handed over as fds, plus a same-host shared-memory ring short-circuit
// for co-located ranks), speaks a fixed binary active-message protocol
// (tagged activation / eager-data / rendezvous GET-request / GET-reply
// frames — no pickle on the hot path), and drains incoming activations
// STRAIGHT into the native engines' ready structures through the
// PtCommIngestVtbl (ptcomm_iface.h) without ever taking the GIL. A
// remote dep-release therefore costs the same as a local one: an atomic
// decrement plus a ready-push on the consumer rank.
//
// Outbound, the engines' GIL-free release sweeps enqueue activations
// onto a lock-free MPSC send queue (Treiber push + consumer-side
// reversal keeps per-producer FIFO order); Python enqueues data payloads
// the same way (eager payloads are copied into the frame at enqueue
// under the GIL, large ones register a Py_buffer and travel
// receiver-pulled: RDV -> GETREQ -> GETREP). Frame order per peer link is
// FIFO, which the data protocol relies on: a producer's eager DATA frame
// always precedes the ACT frames of the tasks consuming it, so eager
// payloads never need gating; rendezvous payloads gate consumer
// readiness inside the engine (rdv_begin/rdv_land) because the pull
// completes after the activation arrives.
//
// Threading/GIL contract:
//   * Python-called methods (register/send_payload/take_payload/reap/...)
//     hold the GIL and only touch mutex-guarded maps + the send queue.
//   * the progress thread NEVER touches Python objects except reading
//     pinned Py_buffer memory (legal without the GIL); releasing those
//     buffers is deferred to reap(), called under the GIL from the
//     runtime's drain hooks.
//   * peers are registered before start() and immutable afterwards.
//
// Malformed input from the wire (truncated frames, oversized lengths,
// unknown kinds, bad ids) is COUNTED and contained — an unknown kind is
// skipped by length, an untrusted length marks the one peer link broken
// — the progress thread itself never dies and never hangs.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <mutex>
#include <new>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "ptcomm_iface.h"
#include "pthist.h"
#include "ptrace_ring.h"

namespace {

// in-lane trace keys (utils/native_trace.py registers the matching
// "ptcomm::*" PBP keywords)
constexpr uint32_t EV_COMM_ACT_TX = 1;   // POINT, id = tids in the frame
constexpr uint32_t EV_COMM_ACT_RX = 2;   // POINT, id = tids ingested
constexpr uint32_t EV_COMM_DATA_TX = 3;  // POINT, id = payload bytes
constexpr uint32_t EV_COMM_DATA_RX = 4;  // POINT, id = payload bytes
constexpr uint32_t EV_COMM_RDV = 5;      // POINT, id = handle (GET issued)
constexpr uint32_t EV_COMM_REP = 6;      // POINT, id = payload bytes served
// cross-rank flow identity (ISSUE 8): every K_ACTS frame carries a
// per-link sequence number in hdr.aux; both ends record a POINT whose id
// encodes (peer_rank << 40) | seq, so the offline multi-rank trace merge
// (tools/trace_reader.merge_traces) can pair each send with the peer's
// ingest and draw one causal flow arrow per cross-rank activation frame
constexpr uint32_t EV_COMM_FRAME_TX = 7;
constexpr uint32_t EV_COMM_FRAME_RX = 8;
// serving-fabric credit flow (ISSUE 11): one POINT per K_CRED frame on
// each end, id = credit count (grants positive, returns negative), so
// merged Perfetto timelines pair admission-control traffic with the
// ACT/DATA frames it gates
constexpr uint32_t EV_FAB_CRED_TX = 9;
constexpr uint32_t EV_FAB_CRED_RX = 10;
constexpr uint64_t FRAME_SEQ_MASK = (1ull << 40) - 1;

inline int64_t frame_flow_id(int peer, uint64_t seq) {
    return (int64_t)(((uint64_t)peer << 40) | (seq & FRAME_SEQ_MASK));
}

// latency histogram slots (pthist.h; names mirrored in utils/hist.py)
constexpr int H_RDV = 0;      // rendezvous GETREQ -> GETREP round trip
constexpr int H_QUEUE = 1;    // activation enqueue -> wire (send-queue lag)
constexpr int N_HISTS = 2;
const char *const HIST_NAMES[N_HISTS] = {"rdv_rtt_ns", "act_queue_ns"};

constexpr uint64_t HELLO_MAGIC = 0x7074636f6d6d0001ull;  // "ptcomm" v1
constexpr uint32_t SHM_MAGIC = 0x50434d52;               // "PCMR"
constexpr uint32_t MAX_BODY = 1u << 26;                  // 64 MiB sanity cap

// wire kinds
constexpr uint8_t K_HELLO = 1;
constexpr uint8_t K_ACTS = 2;    // body = int32 tids[]
constexpr uint8_t K_DATA = 3;    // body = u32 meta_len + meta + payload
constexpr uint8_t K_RDV = 4;     // body = meta; aux = sender handle
constexpr uint8_t K_GETREQ = 5;  // aux = handle (pool/arg echoed)
constexpr uint8_t K_GETREP = 6;  // body = payload; aux = handle
constexpr uint8_t K_BYE = 7;
constexpr uint8_t K_CRED = 8;    // admission credits; layout/flags in
                                 // ptcomm_iface.h (serving fabric)
// queue-internal only (batched into K_ACTS at drain):
constexpr uint8_t K_ACT_ONE = 100;

struct WireHdr {
    uint32_t body_len;
    uint8_t kind;
    uint8_t flags;
    uint16_t src;
    uint32_t pool;
    uint32_t arg;
    uint64_t aux;
};
static_assert(sizeof(WireHdr) == 24, "wire header must be 24 bytes");

// shared-memory ring layout (created+zeroed by the Python side):
//   [0]   u32 magic, u32 cap
//   [64]  u64 head (producer cursor, bytes written)
//   [128] u64 tail (consumer cursor, bytes read)
//   [192] data[cap]
constexpr size_t SHM_HEAD_OFF = 64;
constexpr size_t SHM_TAIL_OFF = 128;
constexpr size_t SHM_DATA_OFF = 192;

struct ShmView {
    uint8_t *base = nullptr;
    size_t map_len = 0;
    std::atomic<uint64_t> *head = nullptr;
    std::atomic<uint64_t> *tail = nullptr;
    uint8_t *data = nullptr;
    uint64_t cap = 0;
};

struct Peer {
    int rank = -1;
    int fd = -1;  // >= 0: TCP transport
    bool is_shm = false;
    ShmView tx, rx;
    std::string inbuf;
    size_t in_off = 0;
    std::string outbuf;
    size_t out_off = 0;
    bool hello_seen = false;
    bool hello_sent = false;
    bool bye = false;
    bool broken = false;
};

struct SendOp {
    SendOp *next = nullptr;
    int32_t dst = 0;
    uint8_t kind = 0;
    uint8_t flags = 0;         // K_CRED: PTCOMM_CRED_GRANT / _RETURN
    uint32_t pool = 0, arg = 0;
    uint64_t aux = 0;
    int64_t t_enq = 0;         // enqueue stamp (act_queue_ns histogram)
    std::string meta;
    std::string inl;           // eager payload / inline body
    uint64_t rdv_handle = 0;   // K_GETREP: body streams from registration
};

struct PoolReg {
    PyObject *obj = nullptr;  // strong ref (taken under the GIL)
    PtCommIngestVtbl v{};
};

struct EarlyFrame {
    WireHdr h;
    std::string body;
};

struct PayloadEntry {
    std::string meta;
    std::string data;
    bool complete = false;
    uint16_t src = 0;
    uint64_t handle = 0;
    int64_t t_req = 0;   // rendezvous pull-issued stamp (rdv_rtt_ns)
};

struct RdvReg {
    Py_buffer buf{};
};

struct Comm {
    PyObject_HEAD
    int my_rank;
    int nb_ranks;
    std::vector<Peer *> *peers;  // index = rank (nullptr for self/absent)
    std::thread *thread;
    std::atomic<bool> running;
    std::atomic<bool> parked;
    int wake_pipe[2];

    std::atomic<SendOp *> sq;  // MPSC Treiber stack

    std::mutex *pools_mu;
    std::unordered_map<uint32_t, PoolReg> *pools;
    std::unordered_map<uint32_t, std::vector<EarlyFrame>> *early;
    // pools already unregistered: their straggler frames DROP (counted),
    // they must not re-park in `early` for a registration that never comes
    std::unordered_set<uint32_t> *retired;

    std::mutex *pay_mu;
    std::unordered_map<uint64_t, PayloadEntry> *payloads;

    std::mutex *rdv_mu;
    std::unordered_map<uint64_t, RdvReg *> *rdv;
    std::vector<RdvReg *> *rdv_release;  // reaped under the GIL
    uint64_t next_handle;

    // serving-fabric credit ledgers (ISSUE 11), keyed (pool << 32 |
    // tenant) per peer rank. `cred_avail[r]`: credits THIS rank may
    // spend toward rank r (inserter side; cred_take debits locally —
    // the zero-round-trip hot path). `cred_out[r]`: credits this rank
    // GRANTED to rank r and not yet returned/reclaimed (target side;
    // the pool's admission headroom reserves them). Both touched under
    // cred_mu by Python calls AND the progress thread's K_CRED dispatch.
    std::mutex *cred_mu;
    std::vector<std::unordered_map<uint64_t, int64_t>> *cred_avail;
    std::vector<std::unordered_map<uint64_t, int64_t>> *cred_out;

    // stats (relaxed atomics, sampled by stats())
    std::atomic<int64_t> acts_tx, acts_rx, act_frames_tx, act_frames_rx;
    std::atomic<int64_t> data_tx, data_rx, rdv_tx, rdv_rx;
    std::atomic<int64_t> getreq_rx, getrep_rx;
    std::atomic<int64_t> bytes_tx, bytes_rx;
    std::atomic<int64_t> frame_errors, early_parked, dropped_sends;
    std::atomic<int64_t> late_frames;   // frames for retired pools, dropped
    std::atomic<int64_t> creds_granted_tx, creds_granted_rx;
    std::atomic<int64_t> creds_spent, creds_reclaimed;
    std::atomic<int64_t> creds_returned_tx, creds_returned_rx;
    std::atomic<int64_t> cred_frames_tx, cred_frames_rx;
    std::atomic<int64_t> wakeups, loops;
    std::atomic<int64_t> out_pending;  // bytes queued but not yet on a wire

    std::atomic<ptrace_ring::State *> trace;
    std::atomic<pthist::State<N_HISTS> *> hist;
    // per-destination K_ACTS frame sequence (flow pairing); touched only
    // by the frame-building side (progress thread or pump), no atomics
    std::vector<uint64_t> *act_seq;
};

inline pthist::State<N_HISTS> *hist_of(Comm *self) {
    pthist::State<N_HISTS> *hs = self->hist.load(std::memory_order_acquire);
    if (hs && !hs->enabled.load(std::memory_order_relaxed)) hs = nullptr;
    return hs;
}

// ---------------------------------------------------------------- helpers

uint64_t pay_key(uint32_t pool, uint32_t slot) {
    return ((uint64_t)pool << 32) | slot;
}

uint64_t cred_key(uint32_t pool, uint32_t tenant) {
    return ((uint64_t)pool << 32) | tenant;
}

void sq_push(Comm *self, SendOp *op) {
    SendOp *h = self->sq.load(std::memory_order_relaxed);
    do {
        op->next = h;
    } while (!self->sq.compare_exchange_weak(h, op, std::memory_order_release,
                                             std::memory_order_relaxed));
    if (self->parked.load(std::memory_order_acquire)) {
        char c = 1;
        ssize_t r = write(self->wake_pipe[1], &c, 1);
        (void)r;  // pipe full == already waking
        self->wakeups.fetch_add(1, std::memory_order_relaxed);
    }
}

// the C entry the engines call from their GIL-free release sweeps
extern "C" void comm_send_act_c(void *comm, int32_t dst, uint32_t pool,
                                int32_t tid) {
    Comm *self = static_cast<Comm *>(comm);
    if (dst < 0 || dst >= self->nb_ranks || dst == self->my_rank ||
        !(*self->peers)[(size_t)dst]) {
        self->dropped_sends.fetch_add(1, std::memory_order_relaxed);
        return;
    }
    SendOp *op = new (std::nothrow) SendOp();
    if (!op) {
        self->dropped_sends.fetch_add(1, std::memory_order_relaxed);
        return;
    }
    op->dst = dst;
    op->kind = K_ACT_ONE;
    op->pool = pool;
    op->arg = (uint32_t)tid;
    if (hist_of(self)) op->t_enq = ptrace_ring::now_ns();
    sq_push(self, op);
}

void put_frame(Comm *self, Peer *p, uint8_t kind, uint32_t pool,
               uint32_t arg, uint64_t aux, const void *b1, size_t l1,
               const void *b2 = nullptr, size_t l2 = 0,
               uint8_t flags = 0) {
    WireHdr h;
    h.body_len = (uint32_t)(l1 + l2);
    h.kind = kind;
    h.flags = flags;
    h.src = (uint16_t)self->my_rank;
    h.pool = pool;
    h.arg = arg;
    h.aux = aux;
    p->outbuf.append(reinterpret_cast<const char *>(&h), sizeof(h));
    if (l1) p->outbuf.append(static_cast<const char *>(b1), l1);
    if (l2) p->outbuf.append(static_cast<const char *>(b2), l2);
    self->out_pending.fetch_add((int64_t)(sizeof(h) + l1 + l2),
                                std::memory_order_relaxed);
}

// ----------------------------------------------------------- progress: tx

int drain_sendq(Comm *self, ptrace_ring::Writer &tw) {
    SendOp *head = self->sq.exchange(nullptr, std::memory_order_acquire);
    if (!head) return 0;
    // the sq exchange (acquire) pairs with the enqueuer's release push:
    // a trace/hist enable sequenced before that push is visible NOW even
    // if the loop-top open ran before the enable landed — re-check here
    // so the first frames after an attach are never silently unrecorded
    if (!tw.st) tw.open(self->trace.load(std::memory_order_acquire));
    pthist::State<N_HISTS> *hs = hist_of(self);
    // reverse the Treiber stack: per-producer FIFO order restored
    SendOp *rev = nullptr;
    while (head) {
        SendOp *nx = head->next;
        head->next = rev;
        rev = head;
        head = nx;
    }
    int n = 0;
    while (rev) {
        SendOp *op = rev;
        Peer *p = (op->dst >= 0 && op->dst < self->nb_ranks)
                      ? (*self->peers)[(size_t)op->dst]
                      : nullptr;
        if (!p || p->broken) {
            if (op->kind == K_GETREP && op->rdv_handle) {
                // the reply will never go out: release the pinned
                // Py_buffer (via reap) instead of leaking it — and
                // letting fini() spin on pins_pending forever
                std::lock_guard<std::mutex> lk(*self->rdv_mu);
                auto it = self->rdv->find(op->rdv_handle);
                if (it != self->rdv->end()) {
                    self->rdv_release->push_back(it->second);
                    self->rdv->erase(it);
                }
            }
            self->dropped_sends.fetch_add(1, std::memory_order_relaxed);
            rev = op->next;
            delete op;
            continue;
        }
        if (op->kind == K_ACT_ONE) {
            // coalesce consecutive activations for the same (dst, pool)
            // into one K_ACTS frame: 4 bytes per tid instead of a frame
            const int64_t h_now = hs ? ptrace_ring::now_ns() : 0;
            std::string ids;
            ids.append(reinterpret_cast<const char *>(&op->arg), 4);
            int32_t dst = op->dst;
            uint32_t pool = op->pool;
            if (h_now && op->t_enq > 0)
                hs->h[H_QUEUE].add(h_now - op->t_enq);
            SendOp *nx = op->next;
            delete op;
            while (nx && nx->kind == K_ACT_ONE && nx->dst == dst &&
                   nx->pool == pool) {
                ids.append(reinterpret_cast<const char *>(&nx->arg), 4);
                if (h_now && nx->t_enq > 0)
                    hs->h[H_QUEUE].add(h_now - nx->t_enq);
                SendOp *nn = nx->next;
                delete nx;
                nx = nn;
            }
            rev = nx;
            // per-link frame sequence rides hdr.aux so the receiver's
            // ingest pairs with this send in the merged timeline
            uint64_t seq = ++(*self->act_seq)[(size_t)dst];
            put_frame(self, p, K_ACTS, pool, 0, seq, ids.data(),
                      ids.size());
            int64_t cnt = (int64_t)(ids.size() / 4);
            self->acts_tx.fetch_add(cnt, std::memory_order_relaxed);
            self->act_frames_tx.fetch_add(1, std::memory_order_relaxed);
            if (tw.st) {
                tw.rec(EV_COMM_ACT_TX, cnt, ptrace_ring::FLAG_POINT);
                tw.rec(EV_COMM_FRAME_TX, frame_flow_id(dst, seq),
                       ptrace_ring::FLAG_POINT);
            }
            n++;
            continue;
        }
        rev = op->next;
        switch (op->kind) {
            case K_DATA: {
                uint32_t ml = (uint32_t)op->meta.size();
                std::string head4(reinterpret_cast<const char *>(&ml), 4);
                head4 += op->meta;
                put_frame(self, p, K_DATA, op->pool, op->arg, 0,
                          head4.data(), head4.size(), op->inl.data(),
                          op->inl.size());
                self->data_tx.fetch_add(1, std::memory_order_relaxed);
                if (tw.st)
                    tw.rec(EV_COMM_DATA_TX, (int64_t)op->inl.size(),
                           ptrace_ring::FLAG_POINT);
                break;
            }
            case K_RDV:
                put_frame(self, p, K_RDV, op->pool, op->arg, op->aux,
                          op->meta.data(), op->meta.size());
                self->rdv_tx.fetch_add(1, std::memory_order_relaxed);
                break;
            case K_GETREQ:
                put_frame(self, p, K_GETREQ, op->pool, op->arg, op->aux,
                          nullptr, 0);
                if (tw.st)
                    tw.rec(EV_COMM_RDV, (int64_t)op->aux,
                           ptrace_ring::FLAG_POINT);
                break;
            case K_GETREP: {
                // the payload streams straight out of the producer's
                // pinned Py_buffer — no GIL, no copy into the op
                RdvReg *reg = nullptr;
                {
                    std::lock_guard<std::mutex> lk(*self->rdv_mu);
                    auto it = self->rdv->find(op->rdv_handle);
                    if (it != self->rdv->end()) {
                        reg = it->second;
                        self->rdv->erase(it);
                    }
                }
                if (!reg) {
                    self->frame_errors.fetch_add(1,
                                                 std::memory_order_relaxed);
                    break;
                }
                put_frame(self, p, K_GETREP, op->pool, op->arg,
                          op->rdv_handle, reg->buf.buf,
                          (size_t)reg->buf.len);
                if (tw.st)
                    tw.rec(EV_COMM_REP, (int64_t)reg->buf.len,
                           ptrace_ring::FLAG_POINT);
                {
                    // the Py_buffer release needs the GIL: defer to reap()
                    std::lock_guard<std::mutex> lk(*self->rdv_mu);
                    self->rdv_release->push_back(reg);
                }
                break;
            }
            case K_CRED:
                put_frame(self, p, K_CRED, op->pool, op->arg, op->aux,
                          nullptr, 0, nullptr, 0, op->flags);
                self->cred_frames_tx.fetch_add(1, std::memory_order_relaxed);
                if (tw.st)
                    tw.rec(EV_FAB_CRED_TX,
                           op->flags == PTCOMM_CRED_RETURN
                               ? -(int64_t)op->aux : (int64_t)op->aux,
                           ptrace_ring::FLAG_POINT);
                break;
            case K_BYE:
                put_frame(self, p, K_BYE, 0, 0, 0, nullptr, 0);
                break;
            default:
                self->frame_errors.fetch_add(1, std::memory_order_relaxed);
        }
        delete op;
        n++;
    }
    return n;
}

int shm_write(ShmView &v, const char *buf, size_t len) {
    uint64_t head = v.head->load(std::memory_order_relaxed);
    uint64_t tail = v.tail->load(std::memory_order_acquire);
    uint64_t space = v.cap - (head - tail);
    if (space == 0) return 0;
    size_t w = len < space ? len : (size_t)space;
    size_t pos = (size_t)(head % v.cap);
    size_t first = (size_t)(v.cap - pos) < w ? (size_t)(v.cap - pos) : w;
    memcpy(v.data + pos, buf, first);
    if (w > first) memcpy(v.data, buf + first, w - first);
    v.head->store(head + w, std::memory_order_release);
    return (int)w;
}

int shm_read(ShmView &v, std::string &out) {
    uint64_t head = v.head->load(std::memory_order_acquire);
    uint64_t tail = v.tail->load(std::memory_order_relaxed);
    uint64_t avail = head - tail;
    if (avail == 0) return 0;
    size_t pos = (size_t)(tail % v.cap);
    size_t first =
        (size_t)(v.cap - pos) < avail ? (size_t)(v.cap - pos) : (size_t)avail;
    out.append(reinterpret_cast<const char *>(v.data + pos), first);
    if (avail > first)
        out.append(reinterpret_cast<const char *>(v.data),
                   (size_t)avail - first);
    v.tail->store(head, std::memory_order_release);
    return (int)avail;
}

int flush_peer(Comm *self, Peer *p) {
    if (p->broken) return 0;
    if (!p->hello_sent) {
        WireHdr h{0, K_HELLO, 0, (uint16_t)self->my_rank, 0, 0, HELLO_MAGIC};
        p->outbuf.insert(0, reinterpret_cast<const char *>(&h), sizeof(h));
        p->hello_sent = true;
        self->out_pending.fetch_add((int64_t)sizeof(h),
                                    std::memory_order_relaxed);
    }
    size_t avail = p->outbuf.size() - p->out_off;
    if (!avail) return 0;
    int n = 0;
    if (p->is_shm) {
        int w = shm_write(p->tx, p->outbuf.data() + p->out_off, avail);
        if (w > 0) {
            p->out_off += (size_t)w;
            self->bytes_tx.fetch_add(w, std::memory_order_relaxed);
            self->out_pending.fetch_sub(w, std::memory_order_relaxed);
            n = 1;
        }
    } else {
        while (avail) {
            ssize_t w = send(p->fd, p->outbuf.data() + p->out_off, avail,
                             MSG_NOSIGNAL);
            if (w > 0) {
                p->out_off += (size_t)w;
                avail -= (size_t)w;
                self->bytes_tx.fetch_add(w, std::memory_order_relaxed);
                self->out_pending.fetch_sub(w, std::memory_order_relaxed);
                n = 1;
                continue;
            }
            if (w < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
            if (w < 0 && errno == EINTR) continue;
            p->broken = true;
            self->out_pending.fetch_sub(
                (int64_t)(p->outbuf.size() - p->out_off),
                std::memory_order_relaxed);
            break;
        }
    }
    if (p->out_off == p->outbuf.size()) {
        p->outbuf.clear();
        p->out_off = 0;
    } else if (p->out_off > (1u << 20)) {
        p->outbuf.erase(0, p->out_off);
        p->out_off = 0;
    }
    return n;
}

// ----------------------------------------------------------- progress: rx

void dispatch_frame(Comm *self, Peer *p, const WireHdr &h, const char *body,
                    ptrace_ring::Writer &tw);

void parse_frames(Comm *self, Peer *p, ptrace_ring::Writer &tw) {
    for (;;) {
        size_t avail = p->inbuf.size() - p->in_off;
        if (avail < sizeof(WireHdr)) break;
        WireHdr h;
        memcpy(&h, p->inbuf.data() + p->in_off, sizeof(h));
        if (!p->hello_seen) {
            if (h.kind != K_HELLO || h.aux != HELLO_MAGIC ||
                h.body_len != 0) {
                // wrong protocol/version on this link: poison it, never
                // guess at frame boundaries
                self->frame_errors.fetch_add(1, std::memory_order_relaxed);
                p->broken = true;
                return;
            }
            p->hello_seen = true;
            p->in_off += sizeof(WireHdr);
            continue;
        }
        if (h.body_len > MAX_BODY) {
            // an untrusted length would desync every later frame: the
            // link is unrecoverable, the process is not
            self->frame_errors.fetch_add(1, std::memory_order_relaxed);
            p->broken = true;
            return;
        }
        if (avail < sizeof(WireHdr) + h.body_len) break;  // partial: wait
        dispatch_frame(self, p, h, p->inbuf.data() + p->in_off + sizeof(h),
                       tw);
        p->in_off += sizeof(WireHdr) + h.body_len;
    }
    if (p->in_off > (1u << 20) || p->in_off == p->inbuf.size()) {
        p->inbuf.erase(0, p->in_off);
        p->in_off = 0;
    }
}

void dispatch_frame(Comm *self, Peer *p, const WireHdr &h, const char *body,
                    ptrace_ring::Writer &tw) {
    switch (h.kind) {
        case K_BYE:
            p->bye = true;
            return;
        case K_ACTS: {
            if (h.body_len % 4) {
                self->frame_errors.fetch_add(1, std::memory_order_relaxed);
                return;
            }
            int64_t cnt = h.body_len / 4;
            std::lock_guard<std::mutex> lk(*self->pools_mu);
            auto it = self->pools->find(h.pool);
            if (it == self->pools->end()) {
                if (self->retired->count(h.pool)) {
                    self->late_frames.fetch_add(1, std::memory_order_relaxed);
                    return;   // straggler for a finished pool: drop
                }
                (*self->early)[h.pool].push_back(
                    EarlyFrame{h, std::string(body, h.body_len)});
                self->early_parked.fetch_add(1, std::memory_order_relaxed);
                return;
            }
            const PtCommIngestVtbl &v = it->second.v;
            for (uint32_t i = 0; i < h.body_len; i += 4) {
                int32_t tid;
                memcpy(&tid, body + i, 4);
                v.act(v.obj, tid);
            }
            self->acts_rx.fetch_add(cnt, std::memory_order_relaxed);
            self->act_frames_rx.fetch_add(1, std::memory_order_relaxed);
            if (tw.st) {
                tw.rec(EV_COMM_ACT_RX, cnt, ptrace_ring::FLAG_POINT);
                if (h.aux)
                    tw.rec(EV_COMM_FRAME_RX, frame_flow_id(h.src, h.aux),
                           ptrace_ring::FLAG_POINT);
            }
            return;
        }
        case K_DATA: {
            if (h.body_len < 4) {
                self->frame_errors.fetch_add(1, std::memory_order_relaxed);
                return;
            }
            uint32_t ml;
            memcpy(&ml, body, 4);
            if (4 + (uint64_t)ml > h.body_len) {
                self->frame_errors.fetch_add(1, std::memory_order_relaxed);
                return;
            }
            std::lock_guard<std::mutex> lk(*self->pools_mu);
            if (self->pools->find(h.pool) == self->pools->end()) {
                if (self->retired->count(h.pool)) {
                    self->late_frames.fetch_add(1, std::memory_order_relaxed);
                    return;
                }
                (*self->early)[h.pool].push_back(
                    EarlyFrame{h, std::string(body, h.body_len)});
                self->early_parked.fetch_add(1, std::memory_order_relaxed);
                return;
            }
            {
                std::lock_guard<std::mutex> pl(*self->pay_mu);
                PayloadEntry &e = (*self->payloads)[pay_key(h.pool, h.arg)];
                e.meta.assign(body + 4, ml);
                e.data.assign(body + 4 + ml, h.body_len - 4 - ml);
                e.complete = true;
                e.src = h.src;
            }
            self->data_rx.fetch_add(1, std::memory_order_relaxed);
            if (tw.st)
                tw.rec(EV_COMM_DATA_RX, (int64_t)(h.body_len - 4 - ml),
                       ptrace_ring::FLAG_POINT);
            return;
        }
        case K_RDV: {
            std::lock_guard<std::mutex> lk(*self->pools_mu);
            auto it = self->pools->find(h.pool);
            if (it == self->pools->end()) {
                if (self->retired->count(h.pool)) {
                    self->late_frames.fetch_add(1, std::memory_order_relaxed);
                    return;
                }
                (*self->early)[h.pool].push_back(
                    EarlyFrame{h, std::string(body, h.body_len)});
                self->early_parked.fetch_add(1, std::memory_order_relaxed);
                return;
            }
            {
                std::lock_guard<std::mutex> pl(*self->pay_mu);
                PayloadEntry &e = (*self->payloads)[pay_key(h.pool, h.arg)];
                e.meta.assign(body, h.body_len);
                e.complete = false;
                e.src = h.src;
                e.handle = h.aux;
                e.t_req = hist_of(self) ? ptrace_ring::now_ns() : 0;
            }
            const PtCommIngestVtbl &v = it->second.v;
            if (v.rdv_begin) v.rdv_begin(v.obj, (int32_t)h.arg);
            // pull: ask the producer to stream the payload
            SendOp *op = new (std::nothrow) SendOp();
            if (op) {
                op->dst = h.src;
                op->kind = K_GETREQ;
                op->pool = h.pool;
                op->arg = h.arg;
                op->aux = h.aux;
                sq_push(self, op);
            }
            self->rdv_rx.fetch_add(1, std::memory_order_relaxed);
            return;
        }
        case K_GETREQ: {
            SendOp *op = new (std::nothrow) SendOp();
            if (!op) return;
            op->dst = h.src;
            op->kind = K_GETREP;
            op->pool = h.pool;
            op->arg = h.arg;
            op->rdv_handle = h.aux;
            sq_push(self, op);
            self->getreq_rx.fetch_add(1, std::memory_order_relaxed);
            return;
        }
        case K_GETREP: {
            // pools_mu held across the rdv_land call: unregister_pool
            // DECREFs the engine only once no dispatch can be inside it
            std::lock_guard<std::mutex> lk(*self->pools_mu);
            auto it = self->pools->find(h.pool);
            if (it == self->pools->end()) {
                // the pool finished (or never registered): do not mint an
                // orphan payload entry nobody will ever take
                self->late_frames.fetch_add(1, std::memory_order_relaxed);
                return;
            }
            {
                std::lock_guard<std::mutex> pl(*self->pay_mu);
                PayloadEntry &e = (*self->payloads)[pay_key(h.pool, h.arg)];
                e.data.assign(body, h.body_len);
                e.complete = true;
                pthist::State<N_HISTS> *hs = hist_of(self);
                if (hs && e.t_req > 0) {
                    // the wire round trip of the rendezvous pull
                    hs->h[H_RDV].add(ptrace_ring::now_ns() - e.t_req);
                    e.t_req = 0;
                }
            }
            if (it->second.v.rdv_land)
                it->second.v.rdv_land(it->second.v.obj, (int32_t)h.arg);
            self->getrep_rx.fetch_add(1, std::memory_order_relaxed);
            if (tw.st)
                tw.rec(EV_COMM_DATA_RX, (int64_t)h.body_len,
                       ptrace_ring::FLAG_POINT);
            return;
        }
        case K_CRED: {
            // admission credits are comm-level (they gate INSERTION, not
            // the engines), so no pool registration is consulted: the
            // ledgers update straight from the progress thread. h.src is
            // wire-supplied and indexes the per-rank ledger vectors, so
            // an out-of-range src is a malformed frame, not an index
            if (h.body_len != 0 || h.aux == 0 ||
                (int)h.src >= self->nb_ranks) {
                self->frame_errors.fetch_add(1, std::memory_order_relaxed);
                return;
            }
            int64_t n = (int64_t)h.aux;
            uint64_t key = cred_key(h.pool, h.arg);
            {
                std::lock_guard<std::mutex> lk(*self->cred_mu);
                if (h.flags == PTCOMM_CRED_RETURN) {
                    // an inserter handed unspent credits back: shrink the
                    // outstanding ledger (floor 0: a return racing a
                    // reclaim must not go negative)
                    int64_t &o = (*self->cred_out)[(size_t)h.src][key];
                    o = o > n ? o - n : 0;
                } else {
                    (*self->cred_avail)[(size_t)h.src][key] += n;
                }
            }
            if (h.flags == PTCOMM_CRED_RETURN)
                self->creds_returned_rx.fetch_add(n,
                                                  std::memory_order_relaxed);
            else
                self->creds_granted_rx.fetch_add(n,
                                                 std::memory_order_relaxed);
            self->cred_frames_rx.fetch_add(1, std::memory_order_relaxed);
            if (tw.st)
                tw.rec(EV_FAB_CRED_RX,
                       h.flags == PTCOMM_CRED_RETURN ? -n : n,
                       ptrace_ring::FLAG_POINT);
            return;
        }
        case K_HELLO:
            return;  // duplicate hello: harmless
        default:
            // unknown kind but trusted length: skip the body, count it —
            // a newer peer speaking an extended protocol must not kill us
            self->frame_errors.fetch_add(1, std::memory_order_relaxed);
            return;
    }
}

// replay frames that arrived before their pool registered (called from
// register_pool, GIL held; pools_mu held by the caller)
void replay_early_locked(Comm *self, uint32_t pool,
                         std::vector<EarlyFrame> &frames) {
    auto it = self->pools->find(pool);
    if (it == self->pools->end()) return;
    const PtCommIngestVtbl &v = it->second.v;
    // replays are the receiver's ingest for frames that raced the pool
    // registration: they must record the same flow points as the live
    // dispatch path, or the merged timeline would report unmatched sends
    ptrace_ring::Writer tw;
    tw.open(self->trace.load(std::memory_order_acquire));
    for (EarlyFrame &f : frames) {
        switch (f.h.kind) {
            case K_ACTS:
                for (uint32_t i = 0; i + 4 <= f.h.body_len; i += 4) {
                    int32_t tid;
                    memcpy(&tid, f.body.data() + i, 4);
                    v.act(v.obj, tid);
                }
                self->acts_rx.fetch_add(f.h.body_len / 4,
                                        std::memory_order_relaxed);
                self->act_frames_rx.fetch_add(1, std::memory_order_relaxed);
                if (tw.st) {
                    tw.rec(EV_COMM_ACT_RX, f.h.body_len / 4,
                           ptrace_ring::FLAG_POINT);
                    if (f.h.aux)
                        tw.rec(EV_COMM_FRAME_RX,
                               frame_flow_id(f.h.src, f.h.aux),
                               ptrace_ring::FLAG_POINT);
                }
                break;
            case K_DATA: {
                if (f.h.body_len < 4) break;
                uint32_t ml;
                memcpy(&ml, f.body.data(), 4);
                if (4 + (uint64_t)ml > f.h.body_len) break;
                std::lock_guard<std::mutex> pl(*self->pay_mu);
                PayloadEntry &e =
                    (*self->payloads)[pay_key(f.h.pool, f.h.arg)];
                e.meta.assign(f.body.data() + 4, ml);
                e.data.assign(f.body.data() + 4 + ml,
                              f.h.body_len - 4 - ml);
                e.complete = true;
                e.src = f.h.src;
                self->data_rx.fetch_add(1, std::memory_order_relaxed);
                break;
            }
            case K_RDV: {
                {
                    std::lock_guard<std::mutex> pl(*self->pay_mu);
                    PayloadEntry &e =
                        (*self->payloads)[pay_key(f.h.pool, f.h.arg)];
                    e.meta.assign(f.body.data(), f.h.body_len);
                    e.complete = false;
                    e.src = f.h.src;
                    e.handle = f.h.aux;
                    e.t_req = hist_of(self) ? ptrace_ring::now_ns() : 0;
                }
                if (v.rdv_begin) v.rdv_begin(v.obj, (int32_t)f.h.arg);
                SendOp *op = new (std::nothrow) SendOp();
                if (op) {
                    op->dst = f.h.src;
                    op->kind = K_GETREQ;
                    op->pool = f.h.pool;
                    op->arg = f.h.arg;
                    op->aux = f.h.aux;
                    sq_push(self, op);
                }
                self->rdv_rx.fetch_add(1, std::memory_order_relaxed);
                break;
            }
            default:
                self->frame_errors.fetch_add(1, std::memory_order_relaxed);
        }
    }
}

int pump_recv(Comm *self, ptrace_ring::Writer &tw) {
    // late-attach visibility: see the matching re-open in drain_sendq
    if (!tw.st) tw.open(self->trace.load(std::memory_order_acquire));
    int n = 0;
    char tmp[65536];
    for (Peer *p : *self->peers) {
        if (!p || p->broken || p->bye) continue;
        if (p->is_shm) {
            int r = shm_read(p->rx, p->inbuf);
            if (r > 0) {
                self->bytes_rx.fetch_add(r, std::memory_order_relaxed);
                n++;
            }
        } else {
            for (;;) {
                ssize_t r = recv(p->fd, tmp, sizeof(tmp), 0);
                if (r > 0) {
                    p->inbuf.append(tmp, (size_t)r);
                    self->bytes_rx.fetch_add(r, std::memory_order_relaxed);
                    n++;
                    if ((size_t)r < sizeof(tmp)) break;
                    continue;
                }
                if (r == 0) {
                    // EOF: a clean peer said BYE first; mid-frame EOF is a
                    // truncated stream (counted, link dropped)
                    if (!p->bye) {
                        if (p->inbuf.size() != p->in_off)
                            self->frame_errors.fetch_add(
                                1, std::memory_order_relaxed);
                        p->broken = true;
                    }
                    break;
                }
                if (errno == EAGAIN || errno == EWOULDBLOCK) break;
                if (errno == EINTR) continue;
                if (!p->bye) p->broken = true;
                break;
            }
        }
        if (p->inbuf.size() - p->in_off >= sizeof(WireHdr))
            parse_frames(self, p, tw);
    }
    return n;
}

// ----------------------------------------------------------- thread main

void progress_main(Comm *self) {
    ptrace_ring::Writer tw;
    int idle = 0;
    std::vector<pollfd> pfds;
    while (self->running.load(std::memory_order_relaxed)) {
        if (!tw.st)
            tw.open(self->trace.load(std::memory_order_acquire));
        else if (tw.st && !tw.st->enabled.load(std::memory_order_relaxed)) {
            tw.close();
        }
        self->loops.fetch_add(1, std::memory_order_relaxed);
        int n = 0;
        n += drain_sendq(self, tw);
        bool fl = false;
        for (Peer *p : *self->peers)
            if (p) fl |= flush_peer(self, p) > 0;
        if (fl) n++;
        n += pump_recv(self, tw);
        if (n) {
            idle = 0;
            continue;
        }
        idle++;
        if (idle < 512) continue;  // pure spin: ~tens of µs of latency
        bool has_shm = false;
        for (Peer *p : *self->peers)
            if (p && p->is_shm && !p->broken && !p->bye) has_shm = true;
        if (has_shm && idle < 4096) {
            // shm traffic cannot rouse a poll(): stay in short naps for
            // a while (a mid-DAG lull is µs–ms scale) so ring latency
            // remains tens of µs, not a poll timeout
            usleep(20);
            continue;
        }
        // park: sockets + the wake pipe rouse us via poll; with shm
        // peers the timeout is the latency floor after a LONG idle
        // (~hundreds of ms of naps above), a wakeup-rate/latency tradeoff
        pfds.clear();
        pfds.push_back(pollfd{self->wake_pipe[0], POLLIN, 0});
        for (Peer *p : *self->peers) {
            if (!p || p->broken || p->bye) continue;
            if (!p->is_shm) pfds.push_back(pollfd{p->fd, POLLIN, 0});
        }
        self->parked.store(true, std::memory_order_release);
        int timeout_ms = has_shm ? 1 : (idle > 8192 ? 20 : 2);
        poll(pfds.data(), (nfds_t)pfds.size(), timeout_ms);
        self->parked.store(false, std::memory_order_release);
        if (pfds[0].revents & POLLIN) {
            char buf[64];
            while (read(self->wake_pipe[0], buf, sizeof(buf)) > 0) {
            }
        }
    }
    tw.close();
}

// ------------------------------------------------------------- Python API

PyObject *comm_new(PyTypeObject *type, PyObject *args, PyObject *) {
    int my_rank, nb_ranks;
    if (!PyArg_ParseTuple(args, "ii", &my_rank, &nb_ranks)) return nullptr;
    if (nb_ranks < 1 || my_rank < 0 || my_rank >= nb_ranks) {
        PyErr_SetString(PyExc_ValueError, "bad rank/nb_ranks");
        return nullptr;
    }
    Comm *self = reinterpret_cast<Comm *>(type->tp_alloc(type, 0));
    if (!self) return nullptr;
    self->my_rank = my_rank;
    self->nb_ranks = nb_ranks;
    self->peers = new (std::nothrow) std::vector<Peer *>((size_t)nb_ranks,
                                                         nullptr);
    self->thread = nullptr;
    new (&self->running) std::atomic<bool>(false);
    new (&self->parked) std::atomic<bool>(false);
    self->wake_pipe[0] = self->wake_pipe[1] = -1;
    new (&self->sq) std::atomic<SendOp *>(nullptr);
    self->pools_mu = new (std::nothrow) std::mutex();
    self->pools = new (std::nothrow) std::unordered_map<uint32_t, PoolReg>();
    self->early = new (std::nothrow)
        std::unordered_map<uint32_t, std::vector<EarlyFrame>>();
    self->retired = new (std::nothrow) std::unordered_set<uint32_t>();
    self->pay_mu = new (std::nothrow) std::mutex();
    self->payloads =
        new (std::nothrow) std::unordered_map<uint64_t, PayloadEntry>();
    self->rdv_mu = new (std::nothrow) std::mutex();
    self->rdv = new (std::nothrow) std::unordered_map<uint64_t, RdvReg *>();
    self->rdv_release = new (std::nothrow) std::vector<RdvReg *>();
    self->next_handle = 1;
    self->cred_mu = new (std::nothrow) std::mutex();
    self->cred_avail = new (std::nothrow)
        std::vector<std::unordered_map<uint64_t, int64_t>>(
            (size_t)nb_ranks);
    self->cred_out = new (std::nothrow)
        std::vector<std::unordered_map<uint64_t, int64_t>>(
            (size_t)nb_ranks);
    for (std::atomic<int64_t> *c :
         {&self->acts_tx, &self->acts_rx, &self->act_frames_tx,
          &self->act_frames_rx, &self->data_tx, &self->data_rx,
          &self->rdv_tx, &self->rdv_rx, &self->getreq_rx, &self->getrep_rx,
          &self->bytes_tx, &self->bytes_rx, &self->frame_errors,
          &self->early_parked, &self->dropped_sends,
          &self->creds_granted_tx, &self->creds_granted_rx,
          &self->creds_spent, &self->creds_reclaimed,
          &self->creds_returned_tx, &self->creds_returned_rx,
          &self->cred_frames_tx, &self->cred_frames_rx, &self->wakeups,
          &self->loops})
        new (c) std::atomic<int64_t>(0);
    new (&self->trace) std::atomic<ptrace_ring::State *>(nullptr);
    new (&self->hist) std::atomic<pthist::State<N_HISTS> *>(nullptr);
    self->act_seq = new (std::nothrow)
        std::vector<uint64_t>((size_t)nb_ranks, 0);
    if (!self->peers || !self->pools_mu || !self->pools || !self->early ||
        !self->retired || !self->pay_mu || !self->payloads ||
        !self->rdv_mu || !self->rdv || !self->rdv_release ||
        !self->cred_mu || !self->cred_avail || !self->cred_out ||
        !self->act_seq) {
        Py_DECREF(self);
        PyErr_NoMemory();
        return nullptr;
    }
    if (pipe(self->wake_pipe) == 0) {
        fcntl(self->wake_pipe[0], F_SETFL, O_NONBLOCK);
        fcntl(self->wake_pipe[1], F_SETFL, O_NONBLOCK);
    }
    return reinterpret_cast<PyObject *>(self);
}

void comm_stop_locked(Comm *self) {
    if (self->running.load(std::memory_order_relaxed)) {
        self->running.store(false, std::memory_order_relaxed);
        char c = 1;
        ssize_t r = write(self->wake_pipe[1], &c, 1);
        (void)r;
        if (self->thread) {
            self->thread->join();
            delete self->thread;
            self->thread = nullptr;
        }
    }
}

void free_sendq(Comm *self) {
    SendOp *head = self->sq.exchange(nullptr, std::memory_order_acquire);
    while (head) {
        SendOp *nx = head->next;
        delete head;
        head = nx;
    }
}

void close_peer(Peer *p) {
    if (p->fd >= 0) close(p->fd);
    if (p->tx.base) munmap(p->tx.base, p->tx.map_len);
    if (p->rx.base) munmap(p->rx.base, p->rx.map_len);
    delete p;
}

void comm_dealloc(PyObject *obj) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    comm_stop_locked(self);
    free_sendq(self);
    if (self->pools)
        for (auto &kv : *self->pools) Py_XDECREF(kv.second.obj);
    if (self->rdv) {
        for (auto &kv : *self->rdv) {
            PyBuffer_Release(&kv.second->buf);
            delete kv.second;
        }
    }
    if (self->rdv_release) {
        for (RdvReg *r : *self->rdv_release) {
            PyBuffer_Release(&r->buf);
            delete r;
        }
    }
    if (self->peers)
        for (Peer *p : *self->peers)
            if (p) close_peer(p);
    if (self->wake_pipe[0] >= 0) close(self->wake_pipe[0]);
    if (self->wake_pipe[1] >= 0) close(self->wake_pipe[1]);
    delete self->peers;
    delete self->pools_mu;
    delete self->pools;
    delete self->early;
    delete self->retired;
    delete self->pay_mu;
    delete self->payloads;
    delete self->rdv_mu;
    delete self->rdv;
    delete self->rdv_release;
    delete self->cred_mu;
    delete self->cred_avail;
    delete self->cred_out;
    delete self->act_seq;
    delete self->trace.load(std::memory_order_acquire);
    delete self->hist.load(std::memory_order_acquire);
    Py_TYPE(obj)->tp_free(obj);
}

bool check_not_started(Comm *self) {
    if (self->running.load(std::memory_order_relaxed)) {
        PyErr_SetString(PyExc_RuntimeError,
                        "peer registration after start()");
        return false;
    }
    return true;
}

bool check_peer_slot(Comm *self, int rank) {
    if (rank < 0 || rank >= self->nb_ranks || rank == self->my_rank) {
        PyErr_SetString(PyExc_ValueError, "bad peer rank");
        return false;
    }
    if ((*self->peers)[(size_t)rank]) {
        PyErr_SetString(PyExc_ValueError, "peer already registered");
        return false;
    }
    return true;
}

PyObject *comm_add_peer_fd(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    int rank, fd;
    if (!PyArg_ParseTuple(args, "ii", &rank, &fd)) return nullptr;
    if (!check_not_started(self) || !check_peer_slot(self, rank))
        return nullptr;
    int nfd = dup(fd);
    if (nfd < 0) {
        PyErr_SetFromErrno(PyExc_OSError);
        return nullptr;
    }
    fcntl(nfd, F_SETFL, fcntl(nfd, F_GETFL, 0) | O_NONBLOCK);
    Peer *p = new (std::nothrow) Peer();
    if (!p) {
        close(nfd);
        return PyErr_NoMemory();
    }
    p->rank = rank;
    p->fd = nfd;
    (*self->peers)[(size_t)rank] = p;
    Py_RETURN_NONE;
}

bool map_shm(const char *name, size_t min_len, ShmView &v) {
    int fd = shm_open(name, O_RDWR, 0);
    if (fd < 0) return false;
    struct stat st;
    if (fstat(fd, &st) != 0 || (size_t)st.st_size < min_len) {
        close(fd);
        return false;
    }
    void *base =
        mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
             MAP_SHARED, fd, 0);
    close(fd);
    if (base == MAP_FAILED) return false;
    uint32_t magic, cap;
    memcpy(&magic, base, 4);
    memcpy(&cap, static_cast<char *>(base) + 4, 4);
    if (magic != SHM_MAGIC || cap == 0 ||
        SHM_DATA_OFF + cap > (size_t)st.st_size) {
        munmap(base, (size_t)st.st_size);
        return false;
    }
    v.base = static_cast<uint8_t *>(base);
    v.map_len = (size_t)st.st_size;
    v.head = reinterpret_cast<std::atomic<uint64_t> *>(v.base + SHM_HEAD_OFF);
    v.tail = reinterpret_cast<std::atomic<uint64_t> *>(v.base + SHM_TAIL_OFF);
    v.data = v.base + SHM_DATA_OFF;
    v.cap = cap;
    return true;
}

PyObject *comm_add_peer_shm(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    int rank;
    const char *tx_name, *rx_name;
    if (!PyArg_ParseTuple(args, "iss", &rank, &tx_name, &rx_name))
        return nullptr;
    if (!check_not_started(self) || !check_peer_slot(self, rank))
        return nullptr;
    Peer *p = new (std::nothrow) Peer();
    if (!p) return PyErr_NoMemory();
    p->rank = rank;
    p->is_shm = true;
    if (!map_shm(tx_name, SHM_DATA_OFF + 16, p->tx) ||
        !map_shm(rx_name, SHM_DATA_OFF + 16, p->rx)) {
        close_peer(p);
        PyErr_Format(PyExc_OSError, "cannot map shm rings %s/%s", tx_name,
                     rx_name);
        return nullptr;
    }
    (*self->peers)[(size_t)rank] = p;
    Py_RETURN_NONE;
}

PyObject *comm_start(PyObject *obj, PyObject *) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    if (self->running.load(std::memory_order_relaxed)) Py_RETURN_NONE;
    if (self->wake_pipe[0] < 0) {
        PyErr_SetString(PyExc_OSError, "wake pipe unavailable");
        return nullptr;
    }
    self->running.store(true, std::memory_order_relaxed);
    self->thread = new (std::nothrow) std::thread(progress_main, self);
    if (!self->thread) {
        self->running.store(false, std::memory_order_relaxed);
        return PyErr_NoMemory();
    }
    Py_RETURN_NONE;
}

// pump(max_iters=1) — synchronous single-threaded progress, for tests
// and single-process loopback use; refuses while the thread runs.
PyObject *comm_pump(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    int iters = 1;
    if (!PyArg_ParseTuple(args, "|i", &iters)) return nullptr;
    if (self->running.load(std::memory_order_relaxed)) {
        PyErr_SetString(PyExc_RuntimeError, "pump() while thread running");
        return nullptr;
    }
    ptrace_ring::Writer tw;
    tw.open(self->trace.load(std::memory_order_acquire));
    int n = 0;
    for (int i = 0; i < iters; i++) {
        n += drain_sendq(self, tw);
        for (Peer *p : *self->peers)
            if (p) n += flush_peer(self, p);
        n += pump_recv(self, tw);
    }
    return PyLong_FromLong(n);
}

PyObject *comm_stop(PyObject *obj, PyObject *) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    // best-effort goodbye so peers see a departure, not a death
    for (Peer *p : *self->peers) {
        if (!p || p->broken) continue;
        SendOp *op = new (std::nothrow) SendOp();
        if (op) {
            op->dst = p->rank;
            op->kind = K_BYE;
            sq_push(self, op);
        }
    }
    if (self->running.load(std::memory_order_relaxed)) {
        // give the thread one chance to flush the BYEs
        usleep(2000);
        comm_stop_locked(self);
    } else {
        ptrace_ring::Writer tw;
        drain_sendq(self, tw);
        for (Peer *p : *self->peers)
            if (p) flush_peer(self, p);
    }
    Py_RETURN_NONE;
}

PyObject *comm_register_pool(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    unsigned int pool;
    PyObject *engine, *cap;
    if (!PyArg_ParseTuple(args, "IOO", &pool, &engine, &cap)) return nullptr;
    PtCommIngestVtbl *v = static_cast<PtCommIngestVtbl *>(
        PyCapsule_GetPointer(cap, PTCOMM_INGEST_CAPSULE));
    if (!v) return nullptr;
    if (v->abi != PTCOMM_ABI) {
        PyErr_SetString(PyExc_RuntimeError, "ptcomm ABI mismatch");
        return nullptr;
    }
    std::vector<EarlyFrame> frames;
    {
        std::lock_guard<std::mutex> lk(*self->pools_mu);
        if (self->pools->count(pool)) {
            PyErr_Format(PyExc_ValueError, "pool %u already registered",
                         pool);
            return nullptr;
        }
        Py_INCREF(engine);
        (*self->pools)[pool] = PoolReg{engine, *v};
        self->retired->erase(pool);
        auto it = self->early->find(pool);
        if (it != self->early->end()) {
            frames.swap(it->second);
            self->early->erase(it);
        }
        if (!frames.empty()) replay_early_locked(self, pool, frames);
    }
    Py_RETURN_NONE;
}

PyObject *comm_unregister_pool(PyObject *obj, PyObject *arg) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    unsigned long pool = PyLong_AsUnsignedLong(arg);
    if (PyErr_Occurred()) return nullptr;
    PyObject *engine = nullptr;
    {
        std::lock_guard<std::mutex> lk(*self->pools_mu);
        auto it = self->pools->find((uint32_t)pool);
        if (it != self->pools->end()) {
            engine = it->second.obj;
            self->pools->erase(it);
        }
        self->retired->insert((uint32_t)pool);
        self->early->erase((uint32_t)pool);
    }
    {
        // drop parked payloads of the retired pool
        std::lock_guard<std::mutex> pl(*self->pay_mu);
        for (auto it = self->payloads->begin();
             it != self->payloads->end();) {
            if ((it->first >> 32) == pool)
                it = self->payloads->erase(it);
            else
                ++it;
        }
    }
    Py_XDECREF(engine);
    Py_RETURN_NONE;
}

PyObject *comm_send_capsule(PyObject *obj, PyObject *) {
    PtCommSendVtbl *v =
        static_cast<PtCommSendVtbl *>(std::malloc(sizeof(PtCommSendVtbl)));
    if (!v) return PyErr_NoMemory();
    v->abi = PTCOMM_ABI;
    v->comm = obj;
    v->send_act = comm_send_act_c;
    PyObject *cap = PyCapsule_New(v, PTCOMM_SEND_CAPSULE, [](PyObject *c) {
        std::free(PyCapsule_GetPointer(c, PTCOMM_SEND_CAPSULE));
    });
    if (!cap) std::free(v);
    return cap;
}

PyObject *comm_send_act(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    int dst;
    unsigned int pool;
    int tid;
    if (!PyArg_ParseTuple(args, "iIi", &dst, &pool, &tid)) return nullptr;
    comm_send_act_c(self, dst, pool, tid);
    Py_RETURN_NONE;
}

// send_payload(dst, pool, slot, meta: bytes, data: buffer, eager_limit)
//   -> "eager" | "rdv"
PyObject *comm_send_payload(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    int dst;
    unsigned int pool, slot;
    Py_buffer meta, data;
    long long eager_limit = 65536;
    if (!PyArg_ParseTuple(args, "iIIy*y*|L", &dst, &pool, &slot, &meta,
                          &data, &eager_limit))
        return nullptr;
    if (dst < 0 || dst >= self->nb_ranks || dst == self->my_rank ||
        !(*self->peers)[(size_t)dst]) {
        PyBuffer_Release(&meta);
        PyBuffer_Release(&data);
        PyErr_SetString(PyExc_ValueError, "bad destination rank");
        return nullptr;
    }
    if ((uint64_t)data.len + (uint64_t)meta.len + 16 > MAX_BODY) {
        // a reply/frame larger than the receiver's untrusted-length cap
        // would poison the link (and a >4 GiB body would wrap the u32
        // length): refuse LOUDLY at the source instead
        PyBuffer_Release(&meta);
        PyBuffer_Release(&data);
        PyErr_Format(PyExc_ValueError,
                     "payload of %lld bytes exceeds the native comm "
                     "lane's %u-byte frame cap",
                     (long long)data.len, (unsigned)MAX_BODY);
        return nullptr;
    }
    const char *mode;
    if (data.len <= eager_limit) {
        SendOp *op = new (std::nothrow) SendOp();
        if (!op) {
            PyBuffer_Release(&meta);
            PyBuffer_Release(&data);
            return PyErr_NoMemory();
        }
        op->dst = dst;
        op->kind = K_DATA;
        op->pool = pool;
        op->arg = slot;
        op->meta.assign(static_cast<const char *>(meta.buf),
                        (size_t)meta.len);
        op->inl.assign(static_cast<const char *>(data.buf),
                       (size_t)data.len);
        PyBuffer_Release(&data);
        sq_push(self, op);
        mode = "eager";
    } else {
        // rendezvous: pin the buffer (the Py_buffer keeps the exporter
        // alive), ship only the descriptor; the receiver pulls
        RdvReg *reg = new (std::nothrow) RdvReg();
        if (!reg) {
            PyBuffer_Release(&meta);
            PyBuffer_Release(&data);
            return PyErr_NoMemory();
        }
        reg->buf = data;  // ownership moves (no release here)
        uint64_t handle;
        {
            std::lock_guard<std::mutex> lk(*self->rdv_mu);
            handle = self->next_handle++;
            (*self->rdv)[handle] = reg;
        }
        SendOp *op = new (std::nothrow) SendOp();
        if (!op) {
            PyBuffer_Release(&meta);
            return PyErr_NoMemory();  // reg stays until fini (leak-safe)
        }
        op->dst = dst;
        op->kind = K_RDV;
        op->pool = pool;
        op->arg = slot;
        op->aux = handle;
        op->meta.assign(static_cast<const char *>(meta.buf),
                        (size_t)meta.len);
        sq_push(self, op);
        mode = "rdv";
    }
    PyBuffer_Release(&meta);
    return PyUnicode_FromString(mode);
}

// take_payload(pool, slot) -> (meta: bytes, data: bytes); KeyError when
// absent or still mid-pull. Consumes (and frees) the stored entry.
PyObject *comm_take_payload(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    unsigned int pool, slot;
    if (!PyArg_ParseTuple(args, "II", &pool, &slot)) return nullptr;
    std::string meta, data;
    {
        std::lock_guard<std::mutex> lk(*self->pay_mu);
        auto it = self->payloads->find(pay_key(pool, slot));
        if (it == self->payloads->end() || !it->second.complete) {
            PyErr_Format(PyExc_KeyError,
                         "no complete payload for pool %u slot %u", pool,
                         slot);
            return nullptr;
        }
        meta.swap(it->second.meta);
        data.swap(it->second.data);
        self->payloads->erase(it);
    }
    return Py_BuildValue("(y#y#)", meta.data(), (Py_ssize_t)meta.size(),
                         data.data(), (Py_ssize_t)data.size());
}

PyObject *comm_payload_ready(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    unsigned int pool, slot;
    if (!PyArg_ParseTuple(args, "II", &pool, &slot)) return nullptr;
    std::lock_guard<std::mutex> lk(*self->pay_mu);
    auto it = self->payloads->find(pay_key(pool, slot));
    if (it != self->payloads->end() && it->second.complete) Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

// reap() -> released pin count; releases Py_buffers whose rendezvous
// replies already streamed out (the progress thread cannot DECREF)
PyObject *comm_reap(PyObject *obj, PyObject *) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    std::vector<RdvReg *> rel;
    {
        std::lock_guard<std::mutex> lk(*self->rdv_mu);
        rel.swap(*self->rdv_release);
    }
    for (RdvReg *r : rel) {
        PyBuffer_Release(&r->buf);
        delete r;
    }
    return PyLong_FromSize_t(rel.size());
}

PyObject *comm_pins_pending(PyObject *obj, PyObject *) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    std::lock_guard<std::mutex> lk(*self->rdv_mu);
    return PyLong_FromSize_t(self->rdv->size());
}

// ----------------------------------------------------- serving credits
// (ISSUE 11; frame layout + flag contract in ptcomm_iface.h)

bool check_cred_args(Comm *self, int rank, long long n, bool want_n) {
    if (rank < 0 || rank >= self->nb_ranks || rank == self->my_rank) {
        PyErr_SetString(PyExc_ValueError, "bad peer rank");
        return false;
    }
    if (want_n && n <= 0) {
        PyErr_SetString(PyExc_ValueError, "credit count must be positive");
        return false;
    }
    return true;
}

// cred_grant(dst, pool, tenant, n): grant n admission credits to rank
// `dst` for (pool, tenant) — bumps the outstanding ledger and ships a
// K_CRED frame. The caller (the fabric) reserves matching window
// headroom on the scheduler plane FIRST.
PyObject *comm_cred_grant(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    int dst;
    unsigned int pool, tenant;
    long long n;
    if (!PyArg_ParseTuple(args, "iIIL", &dst, &pool, &tenant, &n))
        return nullptr;
    if (!check_cred_args(self, dst, n, true)) return nullptr;
    if (!(*self->peers)[(size_t)dst]) {
        PyErr_SetString(PyExc_ValueError, "no such peer");
        return nullptr;
    }
    SendOp *op = new (std::nothrow) SendOp();
    if (!op) return PyErr_NoMemory();
    {
        std::lock_guard<std::mutex> lk(*self->cred_mu);
        (*self->cred_out)[(size_t)dst][cred_key(pool, tenant)] += n;
    }
    self->creds_granted_tx.fetch_add(n, std::memory_order_relaxed);
    op->dst = dst;
    op->kind = K_CRED;
    op->flags = PTCOMM_CRED_GRANT;
    op->pool = pool;
    op->arg = tenant;
    op->aux = (uint64_t)n;
    sq_push(self, op);
    Py_RETURN_NONE;
}

// cred_take(dst, pool, tenant, n=1) -> bool: spend n credits toward
// rank `dst` LOCALLY — one mutex-guarded map op, no wire traffic. False
// = balance exhausted (the remote-admission backpressure signal).
PyObject *comm_cred_take(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    int dst;
    unsigned int pool, tenant;
    long long n = 1;
    if (!PyArg_ParseTuple(args, "iII|L", &dst, &pool, &tenant, &n))
        return nullptr;
    if (!check_cred_args(self, dst, n, true)) return nullptr;
    bool ok = false;
    {
        std::lock_guard<std::mutex> lk(*self->cred_mu);
        auto &m = (*self->cred_avail)[(size_t)dst];
        auto it = m.find(cred_key(pool, tenant));
        if (it != m.end() && it->second >= n) {
            it->second -= n;
            ok = true;
        }
    }
    if (ok) self->creds_spent.fetch_add(n, std::memory_order_relaxed);
    return PyBool_FromLong(ok ? 1 : 0);
}

// cred_return(dst, pool, tenant, n) -> returned: hand up to n unspent
// credits back to the granting rank (a K_CRED return frame); returns
// how many were actually held and returned.
PyObject *comm_cred_return(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    int dst;
    unsigned int pool, tenant;
    long long n;
    if (!PyArg_ParseTuple(args, "iIIL", &dst, &pool, &tenant, &n))
        return nullptr;
    if (!check_cred_args(self, dst, n, true)) return nullptr;
    int64_t take = 0;
    {
        std::lock_guard<std::mutex> lk(*self->cred_mu);
        auto &m = (*self->cred_avail)[(size_t)dst];
        auto it = m.find(cred_key(pool, tenant));
        if (it != m.end() && it->second > 0) {
            take = it->second < n ? it->second : n;
            it->second -= take;
        }
    }
    if (take > 0) {
        SendOp *op = new (std::nothrow) SendOp();
        if (op) {
            op->dst = dst;
            op->kind = K_CRED;
            op->flags = PTCOMM_CRED_RETURN;
            op->pool = pool;
            op->arg = tenant;
            op->aux = (uint64_t)take;
            sq_push(self, op);
            self->creds_returned_tx.fetch_add(take,
                                              std::memory_order_relaxed);
        }
    }
    return PyLong_FromLongLong(take);
}

// cred_consume(src, pool, tenant, n=1) -> consumed: a credited insert
// ARRIVED from rank `src` — shrink the outstanding ledger by the spent
// credit (the target-side half of the local-spend contract; floors at
// 0 so an uncredited or post-reclaim arrival cannot go negative).
PyObject *comm_cred_consume(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    int src;
    unsigned int pool, tenant;
    long long n = 1;
    if (!PyArg_ParseTuple(args, "iII|L", &src, &pool, &tenant, &n))
        return nullptr;
    if (!check_cred_args(self, src, n, true)) return nullptr;
    int64_t took = 0;
    {
        std::lock_guard<std::mutex> lk(*self->cred_mu);
        auto &m = (*self->cred_out)[(size_t)src];
        auto it = m.find(cred_key(pool, tenant));
        if (it != m.end() && it->second > 0) {
            took = it->second < n ? it->second : n;
            it->second -= took;
        }
    }
    return PyLong_FromLongLong(took);
}

PyObject *comm_cred_avail(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    int dst;
    unsigned int pool, tenant;
    if (!PyArg_ParseTuple(args, "iII", &dst, &pool, &tenant))
        return nullptr;
    if (!check_cred_args(self, dst, 1, false)) return nullptr;
    std::lock_guard<std::mutex> lk(*self->cred_mu);
    auto &m = (*self->cred_avail)[(size_t)dst];
    auto it = m.find(cred_key(pool, tenant));
    return PyLong_FromLongLong(it == m.end() ? 0 : it->second);
}

PyObject *comm_cred_outstanding(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    int dst;
    unsigned int pool, tenant;
    if (!PyArg_ParseTuple(args, "iII", &dst, &pool, &tenant))
        return nullptr;
    if (!check_cred_args(self, dst, 1, false)) return nullptr;
    std::lock_guard<std::mutex> lk(*self->cred_mu);
    auto &m = (*self->cred_out)[(size_t)dst];
    auto it = m.find(cred_key(pool, tenant));
    return PyLong_FromLongLong(it == m.end() ? 0 : it->second);
}

// cred_reclaim(rank) -> ([(pool, tenant, outstanding), ...], dropped):
// peer-death containment. Zeroes BOTH ledgers for `rank`: the per-key
// outstanding grants are handed back to the caller so it can release
// the matching scheduler-plane window reservations (no leaked window),
// and `dropped` is the now-unspendable balance this rank held toward
// the dead peer. Idempotent: a second call returns empty.
PyObject *comm_cred_reclaim(PyObject *obj, PyObject *arg) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    long rank = PyLong_AsLong(arg);
    if (rank == -1 && PyErr_Occurred()) return nullptr;
    if (rank < 0 || rank >= self->nb_ranks || rank == self->my_rank) {
        PyErr_SetString(PyExc_ValueError, "bad peer rank");
        return nullptr;
    }
    std::vector<std::pair<uint64_t, int64_t>> out;
    int64_t dropped = 0, reclaimed = 0;
    {
        std::lock_guard<std::mutex> lk(*self->cred_mu);
        for (auto &kv : (*self->cred_out)[(size_t)rank]) {
            if (kv.second > 0) {
                out.emplace_back(kv.first, kv.second);
                reclaimed += kv.second;
            }
        }
        (*self->cred_out)[(size_t)rank].clear();
        for (auto &kv : (*self->cred_avail)[(size_t)rank])
            if (kv.second > 0) dropped += kv.second;
        (*self->cred_avail)[(size_t)rank].clear();
    }
    if (reclaimed)
        self->creds_reclaimed.fetch_add(reclaimed,
                                        std::memory_order_relaxed);
    PyObject *lst = PyList_New((Py_ssize_t)out.size());
    if (!lst) return nullptr;
    for (size_t i = 0; i < out.size(); i++) {
        PyObject *t = Py_BuildValue(
            "(IIL)", (unsigned int)(out[i].first >> 32),
            (unsigned int)(out[i].first & 0xFFFFFFFFu),
            (long long)out[i].second);
        if (!t) { Py_DECREF(lst); return nullptr; }
        PyList_SET_ITEM(lst, (Py_ssize_t)i, t);
    }
    return Py_BuildValue("(NL)", lst, (long long)dropped);
}

PyObject *comm_stats(PyObject *obj, PyObject *) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    size_t npay, nearly;
    {
        std::lock_guard<std::mutex> lk(*self->pay_mu);
        npay = self->payloads->size();
    }
    {
        std::lock_guard<std::mutex> lk(*self->pools_mu);
        nearly = 0;
        for (auto &kv : *self->early) nearly += kv.second.size();
    }
    std::vector<int> broken;
    for (Peer *p : *self->peers)
        if (p && p->broken) broken.push_back(p->rank);
    PyObject *bl = PyList_New((Py_ssize_t)broken.size());
    if (!bl) return nullptr;
    for (size_t i = 0; i < broken.size(); i++)
        PyList_SET_ITEM(bl, (Py_ssize_t)i, PyLong_FromLong(broken[i]));
#define C(name) (long long)self->name.load(std::memory_order_relaxed)
    return Py_BuildValue(
        "{s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,"
        "s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:n,s:n,s:N}",
        "out_pending", C(out_pending),
        "acts_tx", C(acts_tx), "acts_rx", C(acts_rx), "act_frames_tx",
        C(act_frames_tx), "act_frames_rx", C(act_frames_rx), "data_tx",
        C(data_tx), "data_rx", C(data_rx), "rdv_tx", C(rdv_tx), "rdv_rx",
        C(rdv_rx), "getreq_rx", C(getreq_rx), "getrep_rx", C(getrep_rx),
        "bytes_tx", C(bytes_tx), "bytes_rx", C(bytes_rx), "frame_errors",
        C(frame_errors), "early_parked", C(early_parked), "late_frames",
        C(late_frames), "dropped_sends",
        C(dropped_sends),
        "creds_granted_tx", C(creds_granted_tx), "creds_granted_rx",
        C(creds_granted_rx), "creds_spent", C(creds_spent),
        "creds_returned_tx", C(creds_returned_tx), "creds_returned_rx",
        C(creds_returned_rx), "creds_reclaimed", C(creds_reclaimed),
        "cred_frames_tx", C(cred_frames_tx), "cred_frames_rx",
        C(cred_frames_rx),
        "wakeups", C(wakeups), "loops", C(loops),
        "payloads_pending", (Py_ssize_t)npay, "early_pending",
        (Py_ssize_t)nearly, "broken_peers", bl);
#undef C
}

// ------------------------------------------------------------- trace glue

PyObject *comm_trace_enable(PyObject *obj, PyObject *args) {
    Comm *self = reinterpret_cast<Comm *>(obj);
    return ptrace_ring::py_trace_enable(self->trace, args);
}

PyObject *comm_trace_disable(PyObject *obj, PyObject *) {
    return ptrace_ring::py_trace_disable(
        reinterpret_cast<Comm *>(obj)->trace.load(std::memory_order_acquire));
}

PyObject *comm_trace_drain(PyObject *obj, PyObject *) {
    return ptrace_ring::py_trace_drain(
        reinterpret_cast<Comm *>(obj)->trace.load(std::memory_order_acquire));
}

PyObject *comm_trace_dropped(PyObject *obj, PyObject *) {
    return ptrace_ring::py_trace_dropped(
        reinterpret_cast<Comm *>(obj)->trace.load(std::memory_order_acquire));
}

PyObject *comm_monotonic_ns(PyObject *, PyObject *) {
    return PyLong_FromLongLong(ptrace_ring::now_ns());
}

PyObject *comm_hist_enable(PyObject *obj, PyObject *) {
    return pthist::py_hist_enable<N_HISTS>(
        reinterpret_cast<Comm *>(obj)->hist);
}

PyObject *comm_hist_disable(PyObject *obj, PyObject *) {
    return pthist::py_hist_disable<N_HISTS>(
        reinterpret_cast<Comm *>(obj)->hist.load(std::memory_order_acquire));
}

PyObject *comm_hist_snapshot(PyObject *obj, PyObject *) {
    return pthist::py_hist_snapshot<N_HISTS>(
        reinterpret_cast<Comm *>(obj)->hist.load(std::memory_order_acquire),
        HIST_NAMES);
}

PyMethodDef comm_methods[] = {
    {"add_peer_fd", comm_add_peer_fd, METH_VARARGS,
     "add_peer_fd(rank, fd): adopt (dup) a connected stream socket"},
    {"add_peer_shm", comm_add_peer_shm, METH_VARARGS,
     "add_peer_shm(rank, tx_name, rx_name): map a same-host ring pair"},
    {"start", comm_start, METH_NOARGS,
     "launch the funneled progress thread"},
    {"stop", comm_stop, METH_NOARGS,
     "say BYE, flush, stop the progress thread"},
    {"pump", comm_pump, METH_VARARGS,
     "pump(iters=1) -> n: synchronous progress (tests; thread must be off)"},
    {"register_pool", comm_register_pool, METH_VARARGS,
     "register_pool(pool_id, engine, ingest_capsule): route this pool's "
     "frames into the engine (replays early-arrived frames)"},
    {"unregister_pool", comm_unregister_pool, METH_O,
     "unregister_pool(pool_id): drop routing + parked payloads"},
    {"send_capsule", comm_send_capsule, METH_NOARGS,
     "PyCapsule(PtCommSendVtbl) for Graph.comm_bind"},
    {"send_act", comm_send_act, METH_VARARGS,
     "send_act(dst, pool, tid): enqueue one activation (tests/fallback)"},
    {"send_payload", comm_send_payload, METH_VARARGS,
     "send_payload(dst, pool, slot, meta, data, eager_limit=65536) -> "
     "'eager'|'rdv'"},
    {"take_payload", comm_take_payload, METH_VARARGS,
     "take_payload(pool, slot) -> (meta, data); consumes the entry"},
    {"payload_ready", comm_payload_ready, METH_VARARGS,
     "payload_ready(pool, slot) -> bool"},
    {"cred_grant", comm_cred_grant, METH_VARARGS,
     "cred_grant(dst, pool, tenant, n): grant n admission credits to a "
     "remote inserter (K_CRED frame; outstanding ledger bumped)"},
    {"cred_take", comm_cred_take, METH_VARARGS,
     "cred_take(dst, pool, tenant, n=1) -> bool: spend credits LOCALLY "
     "(no wire traffic); False = exhausted (backpressure)"},
    {"cred_return", comm_cred_return, METH_VARARGS,
     "cred_return(dst, pool, tenant, n) -> returned: hand unspent "
     "credits back to the granting rank"},
    {"cred_consume", comm_cred_consume, METH_VARARGS,
     "cred_consume(src, pool, tenant, n=1) -> consumed: a credited "
     "insert arrived — shrink src's outstanding ledger (floors at 0)"},
    {"cred_avail", comm_cred_avail, METH_VARARGS,
     "cred_avail(dst, pool, tenant) -> spendable balance toward dst"},
    {"cred_outstanding", comm_cred_outstanding, METH_VARARGS,
     "cred_outstanding(dst, pool, tenant) -> credits granted to dst and "
     "not yet returned/reclaimed"},
    {"cred_reclaim", comm_cred_reclaim, METH_O,
     "cred_reclaim(rank) -> ([(pool, tenant, n)], dropped): peer-death "
     "containment — zero both ledgers for rank, hand back per-key "
     "outstanding grants so window reservations can be released"},
    {"reap", comm_reap, METH_NOARGS,
     "release Py_buffer pins whose rendezvous replies were served"},
    {"pins_pending", comm_pins_pending, METH_NOARGS,
     "rendezvous registrations not yet pulled"},
    {"stats", comm_stats, METH_NOARGS, "counter snapshot dict"},
    {"trace_enable", comm_trace_enable, METH_VARARGS,
     "arm the in-lane event rings (EV_COMM_*)"},
    {"trace_disable", comm_trace_disable, METH_NOARGS, "stop recording"},
    {"trace_drain", comm_trace_drain, METH_NOARGS,
     "[(ring_id, packed_events_bytes)]"},
    {"trace_dropped", comm_trace_dropped, METH_NOARGS,
     "events lost to ring overflow"},
    {"monotonic_ns", comm_monotonic_ns, METH_NOARGS, "the trace clock"},
    {"hist_enable", comm_hist_enable, METH_NOARGS,
     "arm the wire latency histograms (rdv_rtt_ns, act_queue_ns)"},
    {"hist_disable", comm_hist_disable, METH_NOARGS,
     "stop recording (buckets are kept)"},
    {"hist_snapshot", comm_hist_snapshot, METH_NOARGS,
     "{name: (count, sum_ns, buckets_bytes)} — buckets pack '<496Q'"},
    {nullptr, nullptr, 0, nullptr}};

PyTypeObject CommType = [] {
    PyTypeObject t = {PyVarObject_HEAD_INIT(nullptr, 0)};
    t.tp_name = "parsec_tpu._ptcomm.Comm";
    t.tp_basicsize = sizeof(Comm);
    t.tp_flags = Py_TPFLAGS_DEFAULT;
    t.tp_doc = "native communication lane (funneled progress thread)";
    t.tp_new = comm_new;
    t.tp_dealloc = comm_dealloc;
    t.tp_methods = comm_methods;
    return t;
}();

PyModuleDef ptcomm_module = {
    PyModuleDef_HEAD_INIT, "_ptcomm",
    "native communication lane (see native/src/ptcomm.cpp)", -1,
    nullptr, nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__ptcomm(void) {
    if (PyType_Ready(&CommType) < 0) return nullptr;
    PyObject *m = PyModule_Create(&ptcomm_module);
    if (!m) return nullptr;
    Py_INCREF(&CommType);
    if (PyModule_AddObject(m, "Comm",
                           reinterpret_cast<PyObject *>(&CommType)) < 0) {
        Py_DECREF(&CommType);
        Py_DECREF(m);
        return nullptr;
    }
    if (PyModule_AddIntConstant(m, "EV_COMM_ACT_TX", EV_COMM_ACT_TX) < 0 ||
        PyModule_AddIntConstant(m, "EV_COMM_ACT_RX", EV_COMM_ACT_RX) < 0 ||
        PyModule_AddIntConstant(m, "EV_COMM_DATA_TX", EV_COMM_DATA_TX) < 0 ||
        PyModule_AddIntConstant(m, "EV_COMM_DATA_RX", EV_COMM_DATA_RX) < 0 ||
        PyModule_AddIntConstant(m, "EV_COMM_RDV", EV_COMM_RDV) < 0 ||
        PyModule_AddIntConstant(m, "EV_COMM_REP", EV_COMM_REP) < 0 ||
        PyModule_AddIntConstant(m, "EV_COMM_FRAME_TX", EV_COMM_FRAME_TX) < 0 ||
        PyModule_AddIntConstant(m, "EV_COMM_FRAME_RX", EV_COMM_FRAME_RX) < 0 ||
        PyModule_AddIntConstant(m, "EV_FAB_CRED_TX", EV_FAB_CRED_TX) < 0 ||
        PyModule_AddIntConstant(m, "EV_FAB_CRED_RX", EV_FAB_CRED_RX) < 0 ||
        PyModule_AddIntConstant(m, "CRED_GRANT", PTCOMM_CRED_GRANT) < 0 ||
        PyModule_AddIntConstant(m, "CRED_RETURN", PTCOMM_CRED_RETURN) < 0 ||
        PyModule_AddIntConstant(m, "HIST_BUCKETS", pthist::NBUCKETS) < 0 ||
        PyModule_AddIntConstant(m, "SHM_MAGIC", (long)SHM_MAGIC) < 0 ||
        PyModule_AddIntConstant(m, "SHM_DATA_OFF", (long)SHM_DATA_OFF) < 0) {
        Py_DECREF(m);
        return nullptr;
    }
    return m;
}
