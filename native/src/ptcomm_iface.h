// C-level contract between the native engines (_ptexec, _ptdtd) and the
// native communication lane (_ptcomm).
//
// The three artifacts are SEPARATE CPython extensions (native/Makefile)
// that share no symbols; they link at runtime through PyCapsules carrying
// these plain-C vtables — the same pattern numpy uses for its C API. Both
// directions of the hot path are GIL-free:
//
//   engine -> comm  (PtCommSendVtbl): a task retiring inside the lane
//     walk discovers a remote successor and enqueues an activation onto
//     the comm lane's lock-free send queue — one function call, no GIL,
//     never blocks (the funneled progress thread does the wire work).
//
//   comm -> engine  (PtCommIngestVtbl): the progress thread decodes an
//     incoming activation frame and drops the dependency decrement
//     straight into the engine's ready structures — a remote dep-release
//     costs the same as a local one (the reference's remote_dep_mpi.c
//     release path funneled into parsec_release_local_OUT_dependencies).
//
// Lifetime rules (enforced by parsec_tpu/comm/native.py, which owns both
// ends): the Comm object registers a pool with Py-level references to
// the engine object (INCREF under the GIL at register, DECREF at
// unregister), and a bound engine must be unbound/finished before the
// Comm object is destroyed. The vtables themselves are POD copied by
// value; `obj`/`comm` are borrowed pointers whose validity is exactly the
// registration window.

#ifndef PARSEC_TPU_PTCOMM_IFACE_H
#define PARSEC_TPU_PTCOMM_IFACE_H

#include <stdint.h>

// bump on any layout/semantics change; both sides check before use
#define PTCOMM_ABI 1

// capsule names (PyCapsule_New/Import contract)
#define PTCOMM_INGEST_CAPSULE "parsec_tpu.ptcomm.ingest_vtbl"
#define PTCOMM_SEND_CAPSULE "parsec_tpu.ptcomm.send_vtbl"

extern "C" {

// engine-side entry points the comm progress thread calls (NO GIL):
typedef struct PtCommIngestVtbl {
    int abi;
    void *obj;  // the engine object (ptexec Graph / ptdtd Engine)
    // one arrived activation == one dependency decrement on task `tid`;
    // a task reaching zero enters the engine's ready structure directly
    void (*act)(void *obj, int32_t tid);
    // rendezvous data lifecycle for input slot `slot` (null for engines
    // without data slots): begin gates readiness of consumers, land
    // releases parked consumers once the pulled payload is available
    void (*rdv_begin)(void *obj, int32_t slot);
    void (*rdv_land)(void *obj, int32_t slot);
} PtCommIngestVtbl;

// comm-side entry point the engine release sweep calls (NO GIL):
typedef struct PtCommSendVtbl {
    int abi;
    void *comm;  // the Comm object
    // enqueue one activation for task `tid` of pool `pool` to rank `dst`
    // onto the lock-free send queue; never blocks, never takes the GIL
    void (*send_act)(void *comm, int32_t dst, uint32_t pool, int32_t tid);
} PtCommSendVtbl;

// ---------------------------------------------------------------- ptfab
// Credit frames of the cross-rank serving fabric (ISSUE 11). The frame
// kind K_CRED rides the same wire as ACTS/DATA so admission control and
// work share one FIFO per link; it is comm-internal (no engine vtable
// entry — credits gate INSERTION, which happens above the engines), but
// the flag values are part of the wire contract both ends of a mesh
// must agree on, so they live in this shared header:
//
//   hdr.pool  = comm pool id of the serving taskpool
//   hdr.arg   = tenant id (crc32 of the tenant name, 0 = the pool itself)
//   hdr.aux   = credit count (u64, > 0)
//   hdr.flags = PTCOMM_CRED_GRANT: target -> inserter, adds to the
//               inserter's locally-spendable balance for (dst,pool,tenant);
//               PTCOMM_CRED_RETURN: inserter -> target, hands unspent
//               credits back so the target's outstanding ledger (and with
//               it the pool's admission headroom) shrinks.
//
// Spends are NOT on the wire: an inserter debits its local balance
// (Comm.cred_take, one mutex-guarded map op) and the spent credit is
// implicitly consumed at the target by the arriving insert's normal
// admission accounting — the zero-round-trip hot-path contract.
#define PTCOMM_CRED_GRANT 0
#define PTCOMM_CRED_RETURN 1

}  // extern "C"

#endif  // PARSEC_TPU_PTCOMM_IFACE_H
